"""Partitioner + artifact invariants (SURVEY §4 implication (a)):
every node exactly one owner; boundary symmetry; edge conservation."""

import numpy as np
import pytest

from bnsgcn_tpu.data.artifacts import build_artifacts, load_artifacts, save_artifacts
from bnsgcn_tpu.data.graph import synthetic_graph
from bnsgcn_tpu.data.partitioner import (bfs_partition, comm_volume, edge_cut,
                                         partition_graph, random_partition)


@pytest.fixture(scope="module")
def g():
    return synthetic_graph(n_nodes=120, avg_degree=6, n_feat=7, n_class=4, seed=20)


@pytest.mark.parametrize("method", ["random", "metis"])
def test_every_node_exactly_one_owner(g, method):
    pid = partition_graph(g, 4, method=method, seed=0)
    assert pid.shape == (g.n_nodes,)
    assert pid.min() >= 0 and pid.max() < 4
    # balanced within ceil
    counts = np.bincount(pid, minlength=4)
    assert counts.max() - counts.min() <= max(2, g.n_nodes // 10)


def test_bfs_beats_random_on_cut(g):
    r = edge_cut(g, random_partition(g, 4, 0))
    b = edge_cut(g, bfs_partition(g, 4, 0))
    assert b <= r  # locality-aware should not be worse


def test_quality_metrics_consistent(g):
    pid = random_partition(g, 3, 0)
    assert comm_volume(g, pid) <= edge_cut(g, pid)


def _artifacts(g, P=4):
    pid = partition_graph(g, P, method="random", seed=1)
    return pid, build_artifacts(g, pid)


def test_artifact_inner_partition_of_nodes(g):
    pid, art = _artifacts(g)
    assert art.n_inner.sum() == g.n_nodes
    all_gnid = art.global_nid[art.inner_mask]
    assert sorted(all_gnid.tolist()) == list(range(g.n_nodes))
    # inner rows hold the right per-node data
    for p in range(art.n_parts):
        ids = art.global_nid[p][art.inner_mask[p]]
        np.testing.assert_array_equal(art.feat[p][art.inner_mask[p]], g.feat[ids])
        np.testing.assert_array_equal(art.train_mask[p][art.inner_mask[p]], g.train_mask[ids])
        np.testing.assert_array_equal(art.in_deg[p][art.inner_mask[p]],
                                      g.in_degrees()[ids].astype(np.float32))


def test_artifact_edge_conservation(g):
    """Each global edge appears exactly once: inner edges in the owner of dst,
    cross edges as halo edges of the dst part."""
    pid, art = _artifacts(g)
    total = 0
    for p in range(art.n_parts):
        real = art.dst[p] < art.pad_inner
        total += int(real.sum())
    assert total == g.n_edges


def test_artifact_boundary_symmetry_and_slots(g):
    """bnd[p, j] lists exactly the p-owned sources of cross edges into j, and
    halo edge slots decode back to the correct global node."""
    pid, art = _artifacts(g)
    P, B = art.n_parts, art.pad_boundary
    cross = pid[g.src] != pid[g.dst]
    for p in range(P):
        for j in range(P):
            if p == j:
                assert art.n_b[p, j] == 0
                continue
            m = cross & (pid[g.src] == p) & (pid[g.dst] == j)
            expect = np.unique(g.src[m])
            got = art.global_nid[p][art.bnd[p, j, :art.n_b[p, j]]]
            np.testing.assert_array_equal(np.sort(got), expect)
    # halo edges: reconstruct each edge's global (src, dst) and compare multisets
    for j in range(P):
        real = art.dst[j] < art.pad_inner
        s, d = art.src[j][real], art.dst[j][real]
        halo = s >= art.pad_inner
        q = (s[halo] - art.pad_inner) // B
        k = (s[halo] - art.pad_inner) % B
        src_gl = art.global_nid[q, art.bnd[q, j, k]]
        dst_gl = art.global_nid[j][d[halo]]
        m = cross & (pid[g.dst] == j)
        expect = np.stack([g.src[m], g.dst[m]], 1)
        got = np.stack([src_gl, dst_gl], 1)
        assert sorted(map(tuple, got)) == sorted(map(tuple, expect))
        # inner edges
        inner_s = art.global_nid[j][s[~halo]]
        inner_d = art.global_nid[j][d[~halo]]
        m2 = (pid[g.src] == j) & (pid[g.dst] == j)
        assert sorted(zip(inner_s, inner_d)) == sorted(zip(g.src[m2], g.dst[m2]))


def test_artifact_out_deg_ext(g):
    pid, art = _artifacts(g)
    out_deg = g.out_degrees().astype(np.float32)
    for p in range(art.n_parts):
        np.testing.assert_array_equal(art.out_deg_ext[p, :art.n_inner[p]],
                                      out_deg[art.global_nid[p, :art.n_inner[p]]])
        for q in range(art.n_parts):
            nb = art.n_b[q, p]
            base = art.pad_inner + q * art.pad_boundary
            ids = art.global_nid[q, art.bnd[q, p, :nb]]
            np.testing.assert_array_equal(art.out_deg_ext[p, base:base + nb], out_deg[ids])


def test_artifact_roundtrip(tmp_path, g):
    pid, art = _artifacts(g, P=3)
    save_artifacts(art, str(tmp_path / "parts"))
    art2 = load_artifacts(str(tmp_path / "parts"))
    for k in ["feat", "label", "src", "dst", "bnd", "n_b", "in_deg",
              "out_deg_ext", "global_nid"]:
        np.testing.assert_array_equal(getattr(art, k), getattr(art2, k))
    assert art2.n_train == g.n_train and art2.n_class == g.n_class


def test_single_partition_degenerate(g):
    pid = partition_graph(g, 1)
    art = build_artifacts(g, pid)
    assert art.n_parts == 1
    assert art.n_b.sum() == 0
    real = art.dst[0] < art.pad_inner
    assert int(real.sum()) == g.n_edges


def test_load_artifacts_partial_parts(tmp_path, g):
    pid, art = _artifacts(g, P=4)
    save_artifacts(art, str(tmp_path / "pp"))
    sub = load_artifacts(str(tmp_path / "pp"), parts=[2, 0])
    assert sub.n_parts == 4                       # meta stays global
    np.testing.assert_array_equal(sub.feat[0], art.feat[2])
    np.testing.assert_array_equal(sub.feat[1], art.feat[0])
    np.testing.assert_array_equal(sub.bnd[0], art.bnd[2])
    np.testing.assert_array_equal(sub.n_b, art.n_b)


def test_place_blocks_local_single_host_equivalent(g):
    import jax
    from bnsgcn_tpu.parallel.mesh import make_parts_mesh
    from bnsgcn_tpu.trainer import (local_part_ids, place_blocks,
                                    place_blocks_local)
    pid, art = _artifacts(g, P=4)
    mesh = make_parts_mesh(4)
    assert local_part_ids(mesh) == [0, 1, 2, 3]   # single process hosts all
    blk = {"feat": art.feat, "bnd": art.bnd}
    a = place_blocks(blk, mesh)
    b = place_blocks_local(blk, mesh)
    for k in blk:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))
        assert a[k].sharding == b[k].sharding
