"""Unit tests for tools/trace_comm.py's trace attribution logic.

The parser feeds the Comm(s) fidelity cross-check (reference comm_timer
semantics, helper/timer/comm_timer.py:21-25); these tests pin its three
non-obvious behaviors on a synthetic chrome trace: nested-duplicate launch
dedup, device-event -> host-program attribution by launch order, and the
min-over-lanes wait-stripping estimate.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

from trace_comm import attribute, program_cost  # noqa: E402


def _meta(pid, tid, name):
    return {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": name}}


def _ev(pid, tid, name, ts, dur):
    return {"ph": "X", "pid": pid, "tid": tid, "name": name,
            "ts": ts, "dur": dur}


def make_trace():
    """Host lane launches train_step twice (each as a ~1us-apart duplicate
    pair), then one exchange_only sweep of two back-to-back fires; two
    device lanes carry collectives after each launch."""
    ev = [_meta(1, 0, "python"), _meta(1, 10, "dev0"), _meta(1, 11, "dev1")]
    # step 1 @ t=1000 (duplicate at 1000.5), step 2 @ t=5000 (+dup)
    for t in (1000.0, 1000.5, 5000.0, 5000.5):
        ev.append(_ev(1, 0, "PjitFunction(train_step)", t, 300))
    # one microbench sweep: two consecutive fires @ 9000, 9500 (+dups)
    for t in (9000.0, 9000.2, 9500.0, 9500.2):
        ev.append(_ev(1, 0, "PjitFunction(exchange_only)", t, 100))
    # device collectives: per step, one a2a per lane with asymmetric wait
    # (lane0 waits: dur 50; lane1 arrives last: dur 10) + one all-reduce
    for t0 in (1100.0, 5100.0):
        ev.append(_ev(1, 10, "all-to-all.1", t0, 50))
        ev.append(_ev(1, 11, "all-to-all.1", t0 + 40, 10))
        ev.append(_ev(1, 10, "all-reduce.2", t0 + 60, 7))
        ev.append(_ev(1, 11, "all-reduce.2", t0 + 60, 7))
    # microbench fires: one a2a per lane per fire
    for t0 in (9100.0, 9600.0):
        ev.append(_ev(1, 10, "all-to-all.9", t0, 20))
        ev.append(_ev(1, 11, "all-to-all.9", t0 + 15, 5))
    # a collective before any launch lands in "other"
    ev.append(_ev(1, 10, "all-gather.0", 10.0, 3))
    return ev


def test_launch_dedup_and_sweeps():
    attr = attribute(make_trace())
    assert attr["train_step"]["launches"] == 2
    assert attr["exchange_only"]["launches"] == 2
    assert attr["exchange_only"]["sweeps"] == 1


def test_attribution_categories():
    attr = attribute(make_trace())
    raw, _, nev, nl = program_cost(attr["train_step"], "exchange")
    assert nl == 2 and nev == 2          # 2 steps x 1 a2a per lane
    assert raw == 2 * (50 + 10)
    rraw, _, _, _ = program_cost(attr["train_step"], "reduce")
    assert rraw == 2 * (7 + 7)
    oraw, _, _, _ = program_cost(attr["other"], "reduce")
    assert oraw == 3                     # pre-launch all-gather

    mraw, _, mev, _ = program_cost(attr["exchange_only"], "exchange")
    assert mev == 2 and mraw == 2 * (20 + 5)


def test_min_over_lanes_strips_waiter():
    attr = attribute(make_trace())
    _, est, _, _ = program_cost(attr["train_step"], "exchange")
    # per step the last-arriving lane's span (10) is the true cost
    assert est == 2 * 10
    _, mest, _, _ = program_cost(attr["exchange_only"], "exchange")
    assert mest == 2 * 5


def test_host_lane_collectives_ignored():
    ev = make_trace()
    ev.append(_ev(1, 0, "all-to-all.7", 1200.0, 999))   # python lane
    attr = attribute(ev)
    raw, _, _, _ = program_cost(attr["train_step"], "exchange")
    assert raw == 2 * (50 + 10)


def test_overlap_report_detects_hidden_exchange():
    """--overlap split observability: exchange spans that coincide with
    interior_agg compute on the same device lane count as hidden; scope
    names are matched in the event name OR any string arg (TPU traces put
    the named_scope path in op metadata args)."""
    from bnsgcn_tpu.utils.traceparse import overlap_from_events

    ev = [_meta(1, 0, "python"), _meta(1, 10, "dev0"), _meta(1, 11, "dev1")]
    ev.append(_ev(1, 0, "PjitFunction(train_step)", 1000.0, 300))
    # lane dev0: a2a @ [1100, 1180]; interior fusion @ [1120, 1220] (via
    # args metadata) -> 60 us hidden; frontier afterwards
    ev.append(_ev(1, 10, "all-to-all.3", 1100.0, 80))
    fused = _ev(1, 10, "fusion.7", 1120.0, 100)
    fused["args"] = {"long_name": "jit(train_step)/interior_agg/fusion.7"}
    ev.append(fused)
    ev.append(_ev(1, 11, "frontier_agg/add.1", 1200.0, 40))
    rep = overlap_from_events(ev)
    assert rep is not None and rep["n_steps"] == 1
    assert abs(rep["exchange_ms"] - 0.080) < 1e-9
    assert abs(rep["interior_ms"] - 0.100) < 1e-9
    assert abs(rep["frontier_ms"] - 0.040) < 1e-9
    assert abs(rep["hidden_ms"] - 0.060) < 1e-9
    assert rep["overlapped"]

    # serialized schedule (exchange fully before interior) -> not overlapped
    ev2 = [_meta(1, 0, "python"), _meta(1, 10, "dev0")]
    ev2.append(_ev(1, 0, "PjitFunction(train_step)", 1000.0, 300))
    ev2.append(_ev(1, 10, "all-to-all.3", 1100.0, 80))
    ev2.append(_ev(1, 10, "interior_agg/fusion.7", 1200.0, 100))
    rep2 = overlap_from_events(ev2)
    assert rep2 is not None and not rep2["overlapped"]
    assert rep2["hidden_ms"] == 0.0

    # fused-run trace (no scope spans at all) -> None, caller logs fallback
    assert overlap_from_events(make_trace()) is None


def test_comm_by_axis_classifies_replica_groups():
    """--by-axis breakdown (replica-axis observability): collectives carrying
    HLO replica_groups are attributed to the mesh axis they reduce over in
    the ('replicas','parts') device order (id = r*P + p, replicas outer);
    attribute-stripped events fall back to the op-kind heuristic."""
    from bnsgcn_tpu.utils.traceparse import classify_axis, comm_by_axis

    P, R = 4, 2
    # parts-axis groups: one consecutive run per replica row
    assert classify_axis([[0, 1, 2, 3], [4, 5, 6, 7]], P, R) == "parts"
    # replica-axis groups: stride-P pairs
    assert classify_axis([[0, 4], [1, 5], [2, 6], [3, 7]], P, R) == "replicas"
    # the fused gradient reduce spans the whole mesh
    assert classify_axis([[0, 1, 2, 3, 4, 5, 6, 7]], P, R) == "replicas x parts"
    # 1-D mesh: the full-mesh group IS the parts axis
    assert classify_axis([[0, 1, 2, 3]], 4, 1) == "parts"
    # misaligned consecutive ids (crossing a replica-row boundary) are not
    # a parts-axis group
    assert classify_axis([[2, 3, 4, 5]], P, R) == "unknown"
    assert classify_axis([], P, R) == "unknown"

    ev = [_meta(1, 0, "python"), _meta(1, 10, "dev0")]
    a2a = _ev(1, 10, "all-to-all.1", 100.0, 30)
    a2a["args"] = {"long_name": "all-to-all, replica_groups={{0,1,2,3},{4,5,6,7}}"}
    ev.append(a2a)
    ar = _ev(1, 10, "all-reduce.2", 200.0, 11)
    ar["args"] = {"long_name": "all-reduce, replica_groups={{0,1,2,3,4,5,6,7}}"}
    ev.append(ar)
    # no replica_groups metadata: op-kind heuristic
    ev.append(_ev(1, 10, "collective-permute.3", 300.0, 5))
    ev.append(_ev(1, 10, "all-reduce.4", 400.0, 7))
    # host (python) lane collectives are ignored as everywhere else
    ev.append(_ev(1, 0, "all-to-all.9", 500.0, 999))
    table = comm_by_axis(ev, P, R)
    assert table["parts"]["exchange"] == 30 + 5
    assert table["replicas x parts"]["reduce"] == 11 + 7
    assert "replicas" not in table     # the fused trainer emits none

    # 1-D mesh fallback: reduces land on 'parts'
    table1 = comm_by_axis([_meta(1, 10, "dev0"),
                           _ev(1, 10, "all-reduce.4", 0.0, 7)], 4, 1)
    assert table1["parts"]["reduce"] == 7

    # multi-lane traces reduce with the min-over-lanes estimator (same as
    # program_cost): the waiter lane's 50 us span is rendezvous wait, the
    # last arriver's 10 us is the true op cost — a raw cross-lane sum
    # (60 us) would skew the axis comparison by straggler wait
    ev3 = [_meta(1, 10, "dev0"), _meta(1, 11, "dev1")]
    ev3.append(_ev(1, 10, "all-to-all.1", 100.0, 50))
    ev3.append(_ev(1, 11, "all-to-all.1", 140.0, 10))
    assert comm_by_axis(ev3, P, R)["parts"]["exchange"] == 10


def test_comm_by_axis_classifies_3_axis_groups():
    """3-D ('replicas','parts','feat') mesh observability: a synthetic
    3-axis trace splits halo ('parts'), per-layer feat psum ('feat') and
    fused gradient ('replicas x parts x feat') device time so --by-axis can
    report each. Device id = (r*P + p)*T + f (replicas outer, feat inner —
    parallel/replicas.make_mesh)."""
    from bnsgcn_tpu.utils.traceparse import classify_axis, comm_by_axis

    P, R, T = 2, 2, 2        # ids: r0p0={0,1} r0p1={2,3} r1p0={4,5} r1p1={6,7}
    # feat groups: T consecutive ids per (replica, part), aligned to T
    assert classify_axis([[0, 1], [2, 3], [4, 5], [6, 7]], P, R, T) == "feat"
    # parts groups: stride-T pairs, one per (replica, feat) lane
    assert classify_axis([[0, 2], [1, 3], [4, 6], [5, 7]], P, R, T) == "parts"
    # replica groups: stride P*T
    assert classify_axis([[0, 4], [1, 5], [2, 6], [3, 7]], P, R, T) == "replicas"
    # the fused gradient reduce spans all three axes
    assert classify_axis([[0, 1, 2, 3, 4, 5, 6, 7]], P, R, T) == \
        "replicas x parts x feat"
    # replica-free (1, P, T) mesh labels
    assert classify_axis([[0, 1, 2, 3]], P, 1, T) == "parts x feat"
    assert classify_axis([[0, 1], [2, 3]], P, 1, T) == "feat"
    assert classify_axis([[0, 2], [1, 3]], P, 1, T) == "parts"
    # feat-misaligned consecutive pairs are not a feat group
    assert classify_axis([[1, 2], [5, 6]], P, R, T) == "unknown"
    # 2-D calls (no feat arg) keep their historical labels
    assert classify_axis([[0, 1, 2, 3], [4, 5, 6, 7]], 4, 2) == "parts"

    ev = [_meta(1, 10, "dev0")]
    a2a = _ev(1, 10, "all-to-all.1", 100.0, 30)
    a2a["args"] = {"long_name":
                   "all-to-all, replica_groups={{0,2},{1,3},{4,6},{5,7}}"}
    ev.append(a2a)
    fpsum = _ev(1, 10, "all-reduce.2", 200.0, 13)
    fpsum["args"] = {"long_name":
                     "all-reduce, replica_groups={{0,1},{2,3},{4,5},{6,7}}"}
    ev.append(fpsum)
    grad = _ev(1, 10, "all-reduce.3", 300.0, 9)
    grad["args"] = {"long_name":
                    "all-reduce, replica_groups={{0,1,2,3,4,5,6,7}}"}
    ev.append(grad)
    # attribute-stripped reduce: op-kind fallback lands on the full mesh
    ev.append(_ev(1, 10, "all-reduce.4", 400.0, 4))
    table = comm_by_axis(ev, P, R, T)
    assert table["parts"]["exchange"] == 30
    assert table["feat"]["reduce"] == 13
    assert table["replicas x parts x feat"]["reduce"] == 9 + 4


def test_step_comm_per_epoch_none_without_exchange_events(tmp_path):
    """A trace window holding train_step launches but NO device exchange
    events (observed when the step compiles inside the window on XLA:CPU)
    must report parse failure, not a fabricated 0.0 Comm column — run.py
    then falls back to the [sampled] microbench (round-5 verify finding)."""
    import gzip
    import json

    from bnsgcn_tpu.utils.traceparse import step_comm_per_epoch

    def write_trace(events):
        d = tmp_path / "plugins" / "profile" / "run1"
        d.mkdir(parents=True, exist_ok=True)
        with gzip.open(d / "host.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": events}, f)

    # launches but no collectives -> None
    write_trace([_meta(1, 0, "python"), _meta(1, 10, "dev0"),
                 _ev(1, 0, "PjitFunction(train_step)", 1000.0, 300)])
    assert step_comm_per_epoch(str(tmp_path)) is None

    # healthy window -> per-step seconds
    write_trace(make_trace())
    parsed = step_comm_per_epoch(str(tmp_path))
    assert parsed is not None
    ex_s, rd_s, steps = parsed
    assert steps == 2
    # min-over-lanes: 2 steps x last-arriver span 10 us -> 10us/step
    assert abs(ex_s - 10e-6) < 1e-9
    assert abs(rd_s - 7e-6) < 1e-9

    # missing trace dir -> None, never a throw
    assert step_comm_per_epoch(str(tmp_path / "nope")) is None
