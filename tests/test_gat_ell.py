"""Dense ELL-layout GAT attention == segment-softmax GAT (fwd, grad, e2e)."""

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import sbm_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.evaluate import gather_parts
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.parallel.mesh import make_parts_mesh
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                init_training, place_blocks, place_replicated)


def _setup(g, spec, spmm, P=4, rate=0.5):
    cfg = Config(model="gat", dropout=spec.dropout, heads=spec.heads,
                 n_train=g.n_train, sampling_rate=rate, spmm=spmm)
    mesh = make_parts_mesh(P)
    art = build_artifacts(g, partition_graph(g, P, method="random", seed=1))
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, "gat")
    blk_np.update(fns.extra_blk)
    blk = place_blocks(blk_np, mesh)
    tb = place_replicated(tables, mesh)
    blk["feat0_ext"] = fns.precompute(blk, place_replicated(tables_full, mesh))
    return cfg, mesh, art, fns, blk, tb


def test_gat_ell_forward_matches_segment_sampled():
    g = synthetic_graph(n_nodes=60, avg_degree=5, n_feat=5, n_class=3, seed=51)
    spec = ModelSpec("gat", (5, 8, 3), norm="layer", dropout=0.0, heads=2,
                     use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(2), spec)
    outs = {}
    for spmm in ("ell", "segment"):
        cfg, mesh, art, fns, blk, tb = _setup(g, spec, spmm)
        p = place_replicated(params, mesh)
        s = place_replicated(state, mesh)
        outs[spmm] = gather_parts(art, fns.forward(p, s, jnp.uint32(3), blk,
                                                   tb, jax.random.key(0)))
    np.testing.assert_allclose(outs["ell"], outs["segment"],
                               rtol=2e-4, atol=2e-4)


def test_gat_ell_train_step_matches_segment():
    """Gradients through the dense attention (AD backward) == segment path."""
    g = synthetic_graph(n_nodes=50, avg_degree=4, n_feat=5, n_class=3, seed=52)
    spec = ModelSpec("gat", (5, 8, 3), norm="layer", dropout=0.0, heads=2,
                     use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(3), spec)
    params_np = jax.tree.map(np.asarray, params)
    results = {}
    for spmm in ("ell", "segment"):
        cfg, mesh, art, fns, blk, tb = _setup(g, spec, spmm, rate=1.0)
        p = place_replicated(params_np, mesh)
        s = place_replicated(state, mesh)
        _, _, opt = init_training(cfg, spec, mesh)
        for e in range(3):
            p, s, opt, loss = fns.train_step(p, s, opt, jnp.uint32(e), blk, tb,
                                             jax.random.key(0), jax.random.key(1))
        results[spmm] = (float(loss), jax.tree.map(np.asarray, jax.device_get(p)))
    assert abs(results["ell"][0] - results["segment"][0]) < 1e-4
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4),
                 results["ell"][1], results["segment"][1])


def test_gat_custom_vjp_matches_ad():
    """The transposed-layout custom VJP == plain AD through the same forward
    (incl. presence masks, split rows via a >cap out-degree hub, and the
    edge-deterministic attention dropout)."""
    from bnsgcn_tpu.ops.ell_attention import (_gat_fwd_impl,
                                              build_gat_layouts,
                                              gat_ell_attention)

    rng = np.random.default_rng(7)
    n = 220
    g = synthetic_graph(n_nodes=n, avg_degree=4, n_feat=4, n_class=3, seed=55)
    # hub: node 0 gets 500 extra out-edges -> per-part out-degree above
    # ELL_SPLIT_CAP even after the P=2 split, so the transposed layout
    # exercises split pseudo-rows + chunk combine
    extra_dst = rng.integers(1, n, size=500)
    g.src = np.concatenate([g.src, np.zeros(500, dtype=np.int64)])
    g.dst = np.concatenate([g.dst, extra_dst.astype(np.int64)])
    pid = partition_graph(g, 2, method="random", seed=2)
    art = build_artifacts(g, pid)
    spec_e, arrays_np = build_gat_layouts(art.src, art.dst, art.pad_inner,
                                          art.n_ext)
    assert spec_e.bwd.n_split > 0, "hub did not create split rows"
    arrays = {k: jnp.asarray(v[0]) for k, v in arrays_np.items()}

    heads, fdim = 2, 5
    z = jnp.asarray(rng.normal(size=(art.n_ext, heads, fdim)), jnp.float32)
    el = jnp.asarray(rng.normal(size=(art.n_ext, heads)), jnp.float32)
    er = jnp.asarray(rng.normal(size=(art.pad_inner, heads)), jnp.float32)
    pres = jnp.asarray(
        np.concatenate([np.ones(art.pad_inner, bool),
                        rng.random(art.n_ext - art.pad_inner) < 0.6]))
    cot = jnp.asarray(rng.normal(size=(art.pad_inner, heads, fdim)), jnp.float32)
    key = jax.random.key(9)

    for drop in (0.0, 0.4):
        def loss_custom(z, el, er):
            out = gat_ell_attention(spec_e, arrays, z, el, er, pres, key,
                                    None, drop, True, 0.2)
            return jnp.sum(out * cot)

        def loss_ad(z, el, er):
            out, _ = _gat_fwd_impl(spec_e, arrays, z, el, er, pres, key,
                                   None, drop, True, 0.2)
            return jnp.sum(out * cot)

        v_c, g_c = jax.value_and_grad(loss_custom, argnums=(0, 1, 2))(z, el, er)
        v_a, g_a = jax.value_and_grad(loss_ad, argnums=(0, 1, 2))(z, el, er)
        np.testing.assert_allclose(float(v_c), float(v_a), rtol=1e-5)
        for name, c, a in zip(("d_z", "d_el", "d_er"), g_c, g_a):
            np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                       rtol=2e-4, atol=2e-5,
                                       err_msg=f"{name} drop={drop}")


def test_gat_sampled_forward_matches_numpy_oracle():
    """P=4 rate-0.5 2-layer GAT forward == an independent numpy oracle of the
    reference's sampled-subgraph semantics (train.py:256-297): layer-0
    attention over (inner + sampled-halo) edges with UNSCALED raw features
    (precompute feat tuple path, model.py:111-121), hidden-layer attention
    with 1/ratio-scaled sampled halo activations (feature_buffer.py:117) and
    presence-masked softmax."""
    from bnsgcn_tpu.parallel.sampling import pair_key, pair_sample

    rate = 0.5
    epoch = 3
    g = synthetic_graph(n_nodes=70, avg_degree=5, n_feat=5, n_class=3, seed=57)
    spec = ModelSpec("gat", (5, 8, 3), norm="layer", dropout=0.0, heads=2,
                     use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(12), spec)
    cfg, mesh, art, fns, blk, tb = _setup(g, spec, "ell", P=4, rate=rate)
    p = place_replicated(params, mesh)
    s = place_replicated(state, mesh)
    base_key = jax.random.key(0)
    got = gather_parts(art, fns.forward(p, s, jnp.uint32(epoch), blk, tb,
                                        base_key))

    # ---- oracle: reconstruct the sampled subgraph in numpy ----
    pid = np.zeros(g.n_nodes, np.int64)
    for q in range(4):
        pid[art.global_nid[q][art.inner_mask[q]]] = q
    # boundary lists B(p -> j) = sorted global ids of p's nodes with an edge
    # into j; sample each with the shared-PRNG law
    sampled_edge = np.zeros(g.n_edges, dtype=bool)
    same = pid[g.src] == pid[g.dst]
    sampled_edge[same] = True
    inv_ratio = np.ones(g.n_nodes, dtype=np.float64)  # per (src,dstpart) would
    scale_of_edge = np.ones(g.n_edges, dtype=np.float64)
    for sp in range(4):
        for j in range(4):
            if sp == j:
                continue
            m = (pid[g.src] == sp) & (pid[g.dst] == j)
            if not m.any():
                continue
            blist = np.unique(g.src[m])               # sorted global ids
            nb = len(blist)
            ssz = int(rate * nb)
            key = pair_key(base_key, jnp.uint32(epoch), sp, j)
            pos, valid = pair_sample(key, jnp.int32(nb), jnp.int32(ssz),
                                     art.pad_boundary, art.pad_boundary)
            chosen = set(np.asarray(pos)[np.asarray(valid)].tolist())
            chosen_ids = set(blist[i] for i in chosen)
            emask = m & np.isin(g.src, list(chosen_ids))
            sampled_edge |= emask
            if ssz > 0:
                scale_of_edge[emask] = nb / ssz       # 1/ratio
    es, ed = g.src[sampled_edge], g.dst[sampled_edge]
    escale = scale_of_edge[sampled_edge]

    def np_gat_layer(pl, h_src_per_edge_scale, h_all, h_dst, heads, out):
        w = np.asarray(pl["w"], np.float64)
        al = np.asarray(pl["attn_l"], np.float64)
        ar = np.asarray(pl["attn_r"], np.float64)
        bias = np.asarray(pl["bias"], np.float64).reshape(1, heads, out)
        z = (h_all @ w).reshape(-1, heads, out)
        el = (z * al[None]).sum(-1)
        zd = (h_dst @ w).reshape(-1, heads, out)
        er = (zd * ar[None]).sum(-1)
        res = np.zeros((h_dst.shape[0], heads, out))
        for v in range(h_dst.shape[0]):
            nbr = es[ed == v]
            if len(nbr) == 0:
                continue
            e = el[nbr] + er[v][None]
            e = np.where(e > 0, e, 0.2 * e)
            a = np.exp(e - e.max(0, keepdims=True))
            a = a / a.sum(0, keepdims=True)
            res[v] = np.einsum("uh,uhf->hf", a, z[nbr])
        return res + bias

    feat = np.asarray(g.feat, np.float64)
    # layer 0: unscaled raw features for sampled halos (feat tuple path)
    h = np_gat_layer(params["layer_0"], None, feat, feat, 2, 8).mean(1)
    ln = params["norm_0"]
    mu = h.mean(-1, keepdims=True)
    var = h.var(-1, keepdims=True)
    h = (h - mu) / np.sqrt(var + 1e-5)
    h = h * np.asarray(ln["scale"], np.float64) + np.asarray(ln["bias"], np.float64)
    h = np.maximum(h, 0.0)
    # layer 1: halo activations scaled by 1/ratio on the wire
    h_src = h.copy()
    # per-edge scaling is applied to z via the sender's activation; emulate by
    # computing z per edge: scale h for cross sampled edges
    # (all of u's edges into part j share one scale)
    w1 = np.asarray(params["layer_1"]["w"], np.float64)
    al1 = np.asarray(params["layer_1"]["attn_l"], np.float64)
    ar1 = np.asarray(params["layer_1"]["attn_r"], np.float64)
    b1 = np.asarray(params["layer_1"]["bias"], np.float64).reshape(1, 2, 3)
    z_dst = (h @ w1).reshape(-1, 2, 3)
    er1 = (z_dst * ar1[None]).sum(-1)
    out = np.zeros((g.n_nodes, 2, 3))
    for v in range(g.n_nodes):
        sel = ed == v
        nbr = es[sel]
        sc = escale[sel]
        if len(nbr) == 0:
            continue
        zsrc = ((h_src[nbr] * sc[:, None]) @ w1).reshape(-1, 2, 3)
        el1 = (zsrc * al1[None]).sum(-1)
        e = el1 + er1[v][None]
        e = np.where(e > 0, e, 0.2 * e)
        a = np.exp(e - e.max(0, keepdims=True))
        a = a / a.sum(0, keepdims=True)
        out[v] = np.einsum("uh,uhf->hf", a, zsrc)
    logits = (out + b1).mean(1)
    np.testing.assert_allclose(got, logits, rtol=2e-3, atol=2e-3)


def test_gat_ell_learns_sbm():
    g = sbm_graph(n_nodes=200, n_class=4, n_feat=8, p_in=0.09, p_out=0.005,
                  seed=53)
    spec = ModelSpec("gat", (8, 16, 4), norm="layer", dropout=0.1, heads=2,
                     use_pp=True, train_size=g.n_train)
    cfg, mesh, art, fns, blk, tb = _setup(g, spec, "ell", rate=0.5)
    params, state = init_params(jax.random.key(4), spec)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh)
    first = None
    for e in range(50):
        params, state, opt, loss = fns.train_step(
            params, state, opt, jnp.uint32(e), blk, tb,
            jax.random.key(0), jax.random.key(1))
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.7, (first, float(loss))
