"""Dense ELL-layout GAT attention == segment-softmax GAT (fwd, grad, e2e)."""

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import sbm_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.evaluate import gather_parts
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.parallel.mesh import make_parts_mesh
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                init_training, place_blocks, place_replicated)


def _setup(g, spec, spmm, P=4, rate=0.5):
    cfg = Config(model="gat", dropout=spec.dropout, heads=spec.heads,
                 n_train=g.n_train, sampling_rate=rate, spmm=spmm)
    mesh = make_parts_mesh(P)
    art = build_artifacts(g, partition_graph(g, P, method="random", seed=1))
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, "gat")
    blk_np.update(fns.extra_blk)
    blk = place_blocks(blk_np, mesh)
    tb = place_replicated(tables, mesh)
    blk["feat0_ext"] = fns.precompute(blk, place_replicated(tables_full, mesh))
    return cfg, mesh, art, fns, blk, tb


def test_gat_ell_forward_matches_segment_sampled():
    g = synthetic_graph(n_nodes=60, avg_degree=5, n_feat=5, n_class=3, seed=51)
    spec = ModelSpec("gat", (5, 8, 3), norm="layer", dropout=0.0, heads=2,
                     use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(2), spec)
    outs = {}
    for spmm in ("ell", "segment"):
        cfg, mesh, art, fns, blk, tb = _setup(g, spec, spmm)
        p = place_replicated(params, mesh)
        s = place_replicated(state, mesh)
        outs[spmm] = gather_parts(art, fns.forward(p, s, jnp.uint32(3), blk,
                                                   tb, jax.random.key(0)))
    np.testing.assert_allclose(outs["ell"], outs["segment"],
                               rtol=2e-4, atol=2e-4)


def test_gat_ell_train_step_matches_segment():
    """Gradients through the dense attention (AD backward) == segment path."""
    g = synthetic_graph(n_nodes=50, avg_degree=4, n_feat=5, n_class=3, seed=52)
    spec = ModelSpec("gat", (5, 8, 3), norm="layer", dropout=0.0, heads=2,
                     use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(3), spec)
    params_np = jax.tree.map(np.asarray, params)
    results = {}
    for spmm in ("ell", "segment"):
        cfg, mesh, art, fns, blk, tb = _setup(g, spec, spmm, rate=1.0)
        p = place_replicated(params_np, mesh)
        s = place_replicated(state, mesh)
        _, _, opt = init_training(cfg, spec, mesh)
        for e in range(3):
            p, s, opt, loss = fns.train_step(p, s, opt, jnp.uint32(e), blk, tb,
                                             jax.random.key(0), jax.random.key(1))
        results[spmm] = (float(loss), jax.tree.map(np.asarray, jax.device_get(p)))
    assert abs(results["ell"][0] - results["segment"][0]) < 1e-4
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4),
                 results["ell"][1], results["segment"][1])


def test_gat_ell_learns_sbm():
    g = sbm_graph(n_nodes=200, n_class=4, n_feat=8, p_in=0.09, p_out=0.005,
                  seed=53)
    spec = ModelSpec("gat", (8, 16, 4), norm="layer", dropout=0.1, heads=2,
                     use_pp=True, train_size=g.n_train)
    cfg, mesh, art, fns, blk, tb = _setup(g, spec, "ell", rate=0.5)
    params, state = init_params(jax.random.key(4), spec)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh)
    first = None
    for e in range(50):
        params, state, opt, loss = fns.train_step(
            params, state, opt, jnp.uint32(e), blk, tb,
            jax.random.key(0), jax.random.key(1))
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.7, (first, float(loss))
