"""Partition-sharded serving (serve_router.py + serve_backend.py), the
in-process half: ownership-map loading, fleet routing units, the named
backend-down error (deadline-bounded, never a hang), drain ordering, the
2-backend bitwise contract vs the single-host server (tier A and tier B,
including cross-part closures and post-delta refresh), replica read
consistency, and delta-log compaction relaunch. The subprocess twin lives
in tests/test_serve_dist_e2e.py."""

import json
import os
import socket
import threading
import time
from functools import lru_cache

import jax
import numpy as np
import pytest

from bnsgcn_tpu import serve
from bnsgcn_tpu import serve_backend as sb
from bnsgcn_tpu import serve_router as sr
from bnsgcn_tpu.config import Config, ConfigError
from bnsgcn_tpu.data.graph import sbm_graph
from bnsgcn_tpu.models.gnn import init_params, spec_from_config
from bnsgcn_tpu.parallel import coord


# ----------------------------------------------------------------------------
# shared fixture: one graph + model + full table, partitioned two ways
# ----------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _setup():
    g = sbm_graph(n_nodes=300, n_class=4, n_feat=8, seed=0)
    cfg = Config(dataset="sbm", model="gcn", n_layers=2, n_hidden=8,
                 n_feat=g.n_feat, n_class=g.n_class, n_train=g.n_train,
                 serve_max_batch=16)
    spec = spec_from_config(cfg)
    params, state = init_params(jax.random.key(1), spec)
    from bnsgcn_tpu.evaluate import full_graph_embeddings
    hidden, logits = full_graph_embeddings(params, state, spec, g)
    rng = np.random.default_rng(7)
    owner = rng.integers(0, 2, size=g.n_nodes).astype(np.int32)
    owner[:2] = [0, 1]                      # both parts non-empty
    return g, cfg, params, state, np.asarray(hidden), np.asarray(logits), owner


class _Fleet2:
    """Two (or 2xR) real backends behind a real router server, all
    in-process: TCP routing/fan-out exactly as production, tables sliced
    from ONE precomputed full table (so bitwise comparisons are against
    the same rows the single-host core serves)."""

    def __init__(self, replicas=1, serve_dir="", compact=0):
        g, cfg, params, state, hidden, logits, owner = _setup()
        self.g, self.owner = g, owner
        self.cfg = cfg.replace(part_replicas=replicas,
                               serve_compact_deltas=compact)
        self.rcore = sr.RouterCore(owner, 2, replicas=replicas, hops=2,
                                   log=lambda *a: None)
        self.router = sr.RouterServer(self.rcore, 0, log=lambda *a: None)
        self.cores, self.servers, self.resolvers = [], [], []
        for part in (0, 1):
            for rep in range(replicas):
                c = sb.build_backend_core(
                    self.cfg.replace(serve_part=part, serve_replica=rep),
                    g, owner, params, state, log=lambda *a: None,
                    hidden=hidden, logits=logits)
                if serve_dir:
                    c.serve_dir = serve_dir
                    c.load_serving_state(serve_dir)
                s = sb.BackendServer(c, 0, log=lambda *a: None)
                res = sb.PeerResolver("127.0.0.1", self.router.port)
                c.graph.resolver = res
                self.rcore.fleet.register(part, rep, "127.0.0.1", s.port)
                self.cores.append(c)
                self.servers.append(s)
                self.resolvers.append(res)

    def close(self):
        for s in self.servers:
            s.drain(timeout_s=2.0)
        for c in self.cores:
            c.close()
        for r in self.resolvers:
            r.close()
        self.router.drain(timeout_s=2.0)
        self.rcore.close()


# ----------------------------------------------------------------------------
# ownership map from the training partition artifacts
# ----------------------------------------------------------------------------

def _write_parts(path, n_inner, gnids):
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump({"n_parts": len(gnids), "n_inner": n_inner}, f)
    for p, ids in enumerate(gnids):
        np.savez(os.path.join(path, f"part{p}.npz"),
                 global_nid=np.asarray(ids, dtype=np.int64))


def test_load_owner_map_roundtrip(tmp_path):
    d = str(tmp_path / "parts")
    # padded global_nid rows (-1 tail) exactly as the artifacts write them
    _write_parts(d, [3, 2], [[4, 0, 2, -1], [1, 3, -1, -1]])
    owner = sr.load_owner_map(d)
    assert owner.tolist() == [0, 1, 0, 1, 0]


def test_load_owner_map_named_errors(tmp_path):
    with pytest.raises(ConfigError, match="no partition artifacts"):
        sr.load_owner_map(str(tmp_path / "nope"))
    d = str(tmp_path / "gap")
    _write_parts(d, [3, 2], [[4, 0, -1], [1, 3, -1]])     # node 2 unowned
    with pytest.raises(ConfigError, match="do not cover"):
        sr.load_owner_map(d)
    d = str(tmp_path / "dup")
    _write_parts(d, [3, 2], [[4, 0, 2], [1, 3, 2]])       # node 2 owned twice
    with pytest.raises(ConfigError, match="inconsistent"):
        sr.load_owner_map(d)


def test_router_endpoint_parsing():
    assert sr.router_endpoint(Config(serve_port=1234)) == ("127.0.0.1", 1234)
    assert sr.router_endpoint(Config(serve_router="h0:9")) == ("h0", 9)
    with pytest.raises(ConfigError):
        sr.router_endpoint(Config(serve_router="garbage"))


# ----------------------------------------------------------------------------
# fleet units: registration, round-robin, eviction
# ----------------------------------------------------------------------------

def test_fleet_registration_and_round_robin():
    f = sr.Fleet(2, 2)
    assert f.missing_parts() == [0, 1]
    assert f.pick(0) is None
    assert f.register(0, 0, "a", 1) == "p0.r0"
    assert f.register(0, 1, "a", 2) == "p0.r1"
    assert f.missing_parts() == [1]
    f.register(1, 0, "a", 3)
    assert f.missing_parts() == []
    # round-robin alternates the live replicas of part 0
    assert [f.pick(0) for _ in range(4)] == [0, 1, 0, 1]
    f.evict(0, 0)
    assert [f.pick(0) for _ in range(2)] == [1, 1]
    assert f.replicas_of(0) == [1]
    with pytest.raises(ValueError):
        f.register(2, 0, "a", 4)            # part out of range
    with pytest.raises(ValueError):
        f.register(0, 2, "a", 4)            # replica out of range
    f.close()


def test_part_graph_preserves_single_host_edge_order():
    """The owned-dst CSR restriction is an order-preserving filter: every
    owned node's in/out neighbor lists are exactly the single-host
    DynamicGraph's — the root of the tier-B bitwise contract."""
    g, _, _, _, _, _, owner = _setup()
    dg = serve.DynamicGraph(g)
    pg = sb.PartGraph(g, owner, 0)
    own = np.flatnonzero(owner == 0)[:40]
    for v in own.tolist():
        assert pg.in_nbrs(v) == dg.in_nbrs(v)
        assert pg.out_nbrs(v) == dg.out_nbrs(v)
        assert pg.in_deg_of([v])[0] == dg.in_deg[v]
        assert pg.out_deg_of([v])[0] == dg.out_deg[v]
        assert np.array_equal(pg.feat_rows([v])[0], dg.feat[v])
    remote = int(np.flatnonzero(owner == 1)[0])
    with pytest.raises(serve.HaloCacheMiss):
        pg.in_nbrs(remote)                  # cache-only without a resolver
    with pytest.raises(ValueError, match="mis-routed"):
        pg.local_of(remote)


def test_backend_rejects_unrouted_writes():
    g, cfg, params, state, hidden, logits, owner = _setup()
    core = sb.build_backend_core(cfg.replace(serve_part=0), g, owner,
                                 params, state, log=lambda *a: None,
                                 hidden=hidden, logits=logits)
    try:
        with pytest.raises(ValueError, match="must route"):
            core.add_edges([[0, 1]])
        with pytest.raises(ValueError, match="must route"):
            core.update_feat(0, [0.0] * g.n_feat)
    finally:
        core.close()


# ----------------------------------------------------------------------------
# failure semantics: named error within the deadline, never a hang
# ----------------------------------------------------------------------------

def test_backend_down_is_named_error_not_hang():
    _, _, _, _, _, _, owner = _setup()
    core = sr.RouterCore(owner, 2, hops=2, log=lambda *a: None,
                         route_timeout_s=1.0)
    with socket.socket() as s:              # a port nothing listens on
        s.bind(("127.0.0.1", 0))
        dead = s.getsockname()[1]
    core.fleet.register(0, 0, "127.0.0.1", dead)
    core.fleet.register(1, 0, "127.0.0.1", dead)
    t0 = time.monotonic()
    with pytest.raises(sr.RouteError, match=r"part \d: no live backend"):
        core.predict(0)
    assert time.monotonic() - t0 < 10.0     # bounded by the route deadline
    with core._lock:
        assert core.stats["evictions"] >= 1
    # the dead backend was evicted -> the fleet is no longer ready, which
    # is itself a named error
    with pytest.raises(sr.RouteError, match="fleet not ready"):
        core.predict(0)
    core.close()


def test_router_not_ready_and_drain_ordering():
    _, _, _, _, _, _, owner = _setup()
    core = sr.RouterCore(owner, 2, hops=2, log=lambda *a: None)
    server = sr.RouterServer(core, 0, log=lambda *a: None)
    try:
        port = server.port
        r = serve.request(port, {"op": "ping"})
        assert r["ok"] and r["router"]
        # reads before the fleet is complete: named error, not a hang
        r = serve.request(port, {"op": "predict", "node": 0})
        assert not r["ok"] and "fleet not ready" in r["err"]
        r = serve.request(port, {"op": "register", "part": 0, "replica": 0,
                                 "addr": "127.0.0.1", "port": 1})
        assert r["ok"] and r["id"] == "p0.r0" and r["missing_parts"] == [1]
        r = serve.request(port, {"op": "fleet"})
        assert r["ok"] and r["parts"]["0"][0]["id"] == "p0.r0"
        # drain ordering: client ops are rejected first, while ping/stats/
        # register stay answerable (a late backend must still be able to
        # re-register mid-shutdown)
        server.drain(stop=False)
        assert not serve.request(port, {"op": "predict", "node": 0})["ok"]
        assert serve.request(port, {"op": "ping"})["ok"]
        assert serve.request(port, {"op": "stats"})["ok"]
        assert serve.request(port, {"op": "register", "part": 1,
                                    "replica": 0, "addr": "127.0.0.1",
                                    "port": 2})["ok"]
    finally:
        server.stop()
        core.close()


# ----------------------------------------------------------------------------
# the bitwise contract: 2 sharded backends == one single-host server
# ----------------------------------------------------------------------------

def test_two_backend_fleet_bitwise_vs_single_host():
    g, cfg, params, state, hidden, logits, owner = _setup()
    ref = serve.build_core(cfg, g, params, state, log=lambda *a: None,
                           hidden=hidden, logits=logits)
    fleet = _Fleet2()
    try:
        # tier A: routed lookup == the single-host table row, bitwise
        probe = [0, 1, 7, 123, g.n_nodes - 1]
        for v in probe:
            routed = fleet.rcore.predict(v)
            local = ref.predict(v)
            assert routed["tier"] == "A"
            assert routed["scores"] == local["scores"]
            assert routed["part"] == owner[v]
            assert routed["backend"] == f"p{owner[v]}.r0"
        many = fleet.rcore.predict_many(probe)
        assert [r["scores"] for r in many] == \
               [ref.predict(v)["scores"] for v in probe]

        # a cross-part edge delta: u and v owned by different parts, so the
        # apply fans to both, the mark BFS crosses the boundary, and tier-B
        # closures need remote halo rows
        u = int(np.flatnonzero(owner == 0)[3])
        v = int(np.flatnonzero(owner == 1)[3])
        edges = [[u, v], [v, u]]
        r = fleet.rcore.add_edges(edges)
        ref_r = ref.add_edges(edges)
        assert r["ok"]
        # identical dirty frontier: the distributed mark BFS covers exactly
        # the single-host forward closure
        fleet_dirty = set()
        for c in fleet.cores:
            with c._lock:
                fleet_dirty |= c.dirty
        assert fleet_dirty == ref.dirty
        assert r["dirty_total"] == ref_r["dirty_total"]

        # tier B on dirty nodes (both sides of the cut): bitwise — same
        # closure, same edge order, same compiled program
        dirty_probe = sorted(ref.dirty)[:4] + [u, v]
        for w in set(dirty_probe):
            routed = fleet.rcore.predict(w)
            local = ref.predict(w)
            assert routed["tier"] == local["tier"] == "B", f"node {w}"
            assert routed["scores"] == local["scores"], f"node {w}"

        # post-delta refresh: flush both, then tier A again — bitwise.
        # The tier-B predicts above already refreshed their targets, so the
        # remaining counts must agree with the single-host server's.
        assert fleet.rcore.flush() == ref.flush()
        assert fleet.rcore._dirty_total() == 0
        for w in set(dirty_probe):
            routed = fleet.rcore.predict(w)
            local = ref.predict(w)
            assert routed["tier"] == local["tier"] == "A", f"node {w}"
            assert routed["scores"] == local["scores"], f"node {w}"

        # a feature update routes to the owner and dirties its closure
        newf = np.full(g.n_feat, 0.5, dtype=np.float32)
        fleet.rcore.update_feat(u, newf.tolist())
        ref.update_feat(u, newf)
        routed, local = fleet.rcore.predict(u), ref.predict(u)
        assert routed["tier"] == local["tier"] == "B"
        assert routed["scores"] == local["scores"]

        stats = fleet.rcore.snapshot_stats()
        assert stats["router"] and stats["parts"] == 2
        assert len(stats["backends"]) == 2
        assert {b["backend"] for b in stats["backends"]} == {"p0.r0", "p1.r0"}
        assert all("halo_fetches" in b for b in stats["backends"])
    finally:
        fleet.close()
        ref.close()


def test_replica_read_consistency_and_delta_broadcast():
    """With 2 replicas per part, a routed delta reaches BOTH replicas and
    round-robined reads return identical bytes whichever replica answers."""
    g, cfg, params, state, hidden, logits, owner = _setup()
    fleet = _Fleet2(replicas=2)
    try:
        u = int(np.flatnonzero(owner == 0)[5])
        v = int(np.flatnonzero(owner == 1)[5])
        fleet.rcore.add_edges([[u, v]])
        # every replica journaled the delta and agrees on the dirty set
        per_part: dict = {}
        for c in fleet.cores:
            with c._lock:
                per_part.setdefault(c.part, []).append(
                    (set(c.dirty), c.stats["deltas"]))
        for part, states in per_part.items():
            assert states[0] == states[1], f"part {part} replicas diverged"
        # consecutive reads hit different replicas (round-robin) yet return
        # identical scores, before and after the refresh
        for w in (u, v):
            a = fleet.rcore.predict(w)
            b = fleet.rcore.predict(w)
            assert a["backend"] != b["backend"]
            assert a["scores"] == b["scores"] and a["tier"] == b["tier"]
        fleet.rcore.flush()
        for w in (u, v):
            a, b = fleet.rcore.predict(w), fleet.rcore.predict(w)
            assert a["backend"] != b["backend"]
            assert a["tier"] == b["tier"] == "A"
            assert a["scores"] == b["scores"]
    finally:
        fleet.close()


# ----------------------------------------------------------------------------
# delta-log compaction: snapshot + tail, replay count drops on relaunch
# ----------------------------------------------------------------------------

def test_backend_compaction_snapshot_plus_tail_relaunch(tmp_path):
    g, cfg, params, state, hidden, logits, owner = _setup()
    serve_dir = str(tmp_path / "sdir")
    fleet = _Fleet2(serve_dir=serve_dir, compact=4)
    own0 = np.flatnonzero(owner == 0)
    try:
        # 6 routed deltas -> each backend journals >= 6 entries (apply +
        # mark shards) and compacts past the threshold of 4
        for i in range(6):
            fleet.rcore.add_edges([[int(own0[i]), int(own0[i + 1])]])
        back0 = next(c for c in fleet.cores if c.part == 0)
        with back0._lock:
            folded0, tail0 = back0._folded, len(back0.deltas)
        assert folded0 >= 4                     # compaction actually ran
        assert tail0 < folded0 + tail0          # log truncated to a tail
        assert os.path.exists(os.path.join(serve_dir, back0._snapshot_name))
        expect_dirty = dict()
        for c in fleet.cores:
            with c._lock:
                expect_dirty[c.part] = set(c.dirty) | set(c._refreshing)
        fleet.rcore.close()                     # drop pooled reads first
        for s in fleet.servers:
            s.drain(timeout_s=2.0)
        for c in fleet.cores:
            c.flush_delta_log(serve_dir)
            c.close()

        # relaunch both parts from the same serve_dir: the snapshot holds
        # the folded deltas, the tail log replays the rest — replayed <
        # total, and the dirty sets come back exactly
        for part in (0, 1):
            c2 = sb.build_backend_core(
                cfg.replace(serve_part=part), g, owner, params, state,
                log=lambda *a: None, hidden=hidden, logits=logits)
            c2.serve_dir = serve_dir
            counts = c2.load_serving_state(serve_dir)
            try:
                if part == 0:
                    assert counts["folded"] == folded0
                    assert counts["replayed"] == tail0
                assert counts["folded"] >= 4 or part != 0
                with c2._lock:
                    assert set(c2.dirty) == expect_dirty[part]
            finally:
                c2.close()
    finally:
        for r in fleet.resolvers:
            r.close()
        fleet.router.drain(timeout_s=2.0)


def test_pooled_client_survives_server_side_idle_drop():
    """LineJsonClient (the router's pooled read path) reconnects once on a
    torn pooled connection — the coord handler drops idle connections at
    its 10 s read timeout, and an evicted pool entry must look like one
    transparent retry, not an error."""
    got = []

    def handler(req):
        got.append(req)
        return {"ok": True, "n": len(got)}

    srv = coord.LineJsonServer(0, handler).start()
    try:
        cli = coord.LineJsonClient("127.0.0.1", srv.port, timeout_s=5.0)
        assert cli.request({"op": "a"})["n"] == 1
        assert cli.request({"op": "b"})["n"] == 2   # same pooled connection
        # kill the server socket under the pooled client, then restart on
        # the SAME port: the retry path must transparently reconnect
        port = srv.port
        srv.stop()
        srv = coord.LineJsonServer(port, handler).start()
        assert cli.request({"op": "c"})["ok"]
        cli.close()
        with pytest.raises(coord.CoordTimeout, match="unreachable"):
            dead = coord.LineJsonClient("127.0.0.1", 1, timeout_s=0.5,
                                        what="nobody")
            dead.request({"op": "x"})
    finally:
        srv.stop()
