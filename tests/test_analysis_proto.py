"""graftcheck-proto (bnsgcn_tpu/analysis/proto/): protocol model checking.

Seeded-protocol-bug fixtures per invariant — each named bug in
analysis/proto/seeded.py reverts one design decision of the coordination
protocol (confirm barrier, doubled ack windows, prune horizon, file
boot-token pinning, worst-wins reduction) and the checker MUST catch it
with the documented rule and a replayable minimized schedule — plus unit
coverage for the deterministic scheduler (replay determinism, hang
detection, DFS enumeration) and the quickgate clean-at-HEAD gate:
`python -m bnsgcn_tpu.analysis proto` explores >= 1000 schedules across
>= 8 scenarios with zero findings inside the CI budget.
"""

import json
import os
import re
import subprocess
import sys

import pytest

from bnsgcn_tpu.analysis.proto import run_proto_audit, run_replay
from bnsgcn_tpu.analysis.proto.explore import run_schedule
from bnsgcn_tpu.analysis.proto.scenarios import ALL_SCENARIOS
from bnsgcn_tpu.analysis.proto.sim import Scheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_REPLAY_RE = re.compile(r"--replay '([^']+)'")


def _env():
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO)
    return env


# ----------------------------------------------------------------------------
# the scheduler itself
# ----------------------------------------------------------------------------

def test_scheduler_replay_is_deterministic(tmp_path):
    """Same scenario + fault + prescribed prefix => identical trail,
    outcomes, and op trace — the property every --replay rests on."""
    scenario = next(s for s in ALL_SCENARIOS if s.name == "agree-ok")
    a = run_schedule(scenario, 0, [1, 0, 1], str(tmp_path), None)
    b = run_schedule(scenario, 0, [1, 0, 1], str(tmp_path), None)
    assert a.choices == b.choices
    assert a.outcomes == b.outcomes
    assert [t[1:] for t in a.trace] == [t[1:] for t in b.trace]


def test_scheduler_detects_hang():
    sched = Scheduler(time_budget=1.0)

    def stuck():
        while True:
            sched.sleep(10.0)       # sleeps forever past the budget

    sched.spawn(0, stuck)
    sched.run()
    assert sched.hung
    assert sched.actors[0].state == "aborted"   # unwound, thread joined


def test_dfs_explores_distinct_schedules(tmp_path):
    scenario = next(s for s in ALL_SCENARIOS if s.name == "broadcast-resume")
    seen = set()
    prefix = []
    for _ in range(50):
        rec = run_schedule(scenario, 0, prefix, str(tmp_path), None)
        key = tuple(rec.choices)
        assert key not in seen      # every DFS step is a NEW interleaving
        seen.add(key)
        from bnsgcn_tpu.analysis.proto.explore import _next_prefix
        nxt = _next_prefix(rec.choices, rec.options)
        if nxt is None:
            break
        prefix = nxt
    assert len(seen) > 1


# ----------------------------------------------------------------------------
# seeded protocol bugs: each must be caught, with a working replay
# ----------------------------------------------------------------------------

SEEDED = [
    # (bug, scenario that catches it, rule that must fire)
    ("confirm-removed", "agree-preempt", "proto-exit-code"),
    ("ack-window-dropped", "slow-decide", "proto-exit-code"),
    ("retire-horizon-1", "retirement-lag", "proto-retired-live-key"),
    ("pin-before-get", "file-relaunch", "proto-exit-code"),
    ("reduce-order-flipped", "agree-worst-wins", "proto-reduce-order"),
    ("rejoin-token-unchecked", "rejoin-stale-token", "proto-exit-code"),
    ("failover-retries-nonidempotent-write", "wal-replay-vs-live-delta",
     "proto-duplicate-write"),
]


@pytest.mark.parametrize("bug,scenario,rule", SEEDED,
                         ids=[b for b, _, _ in SEEDED])
def test_seeded_bug_caught_and_replayable(bug, scenario, rule):
    report = run_proto_audit(scenarios=[scenario], max_schedules=400,
                             seed_bug=bug)
    assert report["ok"] is False
    assert rule in report["counts"], report["counts"]
    finding = next(f for f in report["findings"] if f["rule"] == rule)
    assert finding["file"].startswith(f"proto://{scenario}#")
    spec = _REPLAY_RE.search(finding["message"]).group(1)
    # the minimized schedule reproduces the violation under the seed...
    rep = run_replay(spec, seed_bug=bug)
    assert rep["ok"] is False
    assert rule in {v["rule"] for v in rep["violations"]}
    # ...and the SAME schedule is clean on the real protocol at HEAD
    assert run_replay(spec)["ok"] is True


def test_unknown_seed_bug_and_bad_spec_raise():
    with pytest.raises(ValueError, match="unknown seeded bug"):
        run_proto_audit(scenarios=["agree-ok"], max_schedules=100,
                        seed_bug="no-such-bug")
    with pytest.raises(ValueError, match="bad replay spec"):
        run_replay("not-a-spec")
    with pytest.raises(ValueError, match="unknown scenario"):
        run_proto_audit(scenarios=["no-such-scenario"])


# ----------------------------------------------------------------------------
# elastic RESIZE scenarios: the default schedules pin the verdict shapes
# ----------------------------------------------------------------------------

def test_elastic_scenarios_resize_through_rank_loss(tmp_path):
    """crash-during-resize fault 'shrink' (rank 2 dies at its first
    heartbeat): both survivors finish DONE — no exit code at all — on the
    same shrunken member set, restored at the agreed epoch."""
    s = next(x for x in ALL_SCENARIOS if x.name == "crash-during-resize")
    assert [n for n, _ in s.faults()][1] == "shrink"
    rec = run_schedule(s, 1, [], str(tmp_path), None)
    assert rec.outcomes[2] == ("crashed",)
    vals = {r: json.loads(o[1]) for r, o in rec.outcomes.items()
            if o[0] == "done"}
    assert set(vals) == {0, 1}
    assert all(v == {"resizes": 1, "members": [0, 1]} for v in vals.values())


def test_elastic_scenario_rejoin_skips_stale_grant(tmp_path):
    """rejoin-stale-token nominal: the joiner reads the planted stale
    grant, skips it on the token mismatch, and adopts the fresh one —
    both ranks converge on the grown member set and the same seq."""
    s = next(x for x in ALL_SCENARIOS if x.name == "rejoin-stale-token")
    rec = run_schedule(s, 0, [], str(tmp_path), None)
    vals = {r: json.loads(o[1]) for r, o in rec.outcomes.items()
            if o[0] == "done"}
    assert set(vals) == {0, 1}
    assert vals[0] == vals[1]
    assert vals[0]["members"] == [0, 1] and vals[0]["restart"] == 6


# ----------------------------------------------------------------------------
# CLI + obs event
# ----------------------------------------------------------------------------

def test_cli_audit_emits_proto_audit_event(tmp_path):
    log = tmp_path / "obs.jsonl"
    r = subprocess.run(
        [sys.executable, "-m", "bnsgcn_tpu.analysis", "proto", "-q",
         "--scenario", "broadcast-resume,agree-preempt",
         "--max-schedules", "200", "--json", "-",
         "--obs-log", str(log)],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=_env())
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(r.stdout)
    assert data["ok"] is True and data["n_scenarios"] == 2
    from bnsgcn_tpu.obs import load_events
    evs = [e for e in load_events(str(log)) if e.get("kind") == "proto_audit"]
    assert len(evs) == 1 and evs[0]["ok"] is True
    assert evs[0]["n_schedules"] == data["n_schedules"]
    # the report renderer gives the preflight verdict its own section
    rep = subprocess.run(
        [sys.executable, "tools/obs_report.py", str(log)],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=_env())
    assert rep.returncode == 0, rep.stdout + rep.stderr
    assert "proto_audit: clean" in rep.stdout


def test_cli_replay_bad_spec_exits_2():
    r = subprocess.run(
        [sys.executable, "-m", "bnsgcn_tpu.analysis", "proto",
         "--replay", "bogus"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=_env())
    assert r.returncode == 2
    assert "bad replay spec" in r.stderr


# ----------------------------------------------------------------------------
# the gate
# ----------------------------------------------------------------------------

@pytest.mark.quickgate
def test_proto_audit_clean_at_head(tmp_path):
    """The gate: the real Coordinator/ResilienceManager protocol explores
    clean at HEAD — >= 1000 distinct schedules across >= 8 scenarios
    (crashes, delays, torn acks, stale boot tokens, duplicate relaunches)
    with zero findings and zero explore errors, inside the CI budget."""
    rep = tmp_path / "proto.json"
    r = subprocess.run(
        [sys.executable, "-m", "bnsgcn_tpu.analysis", "proto", "-q",
         "--json", str(rep)],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=_env())
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(rep.read_text())
    assert data["ok"] is True and data["findings"] == []
    assert data["errors"] == []
    assert data["n_scenarios"] >= 8
    assert data["n_schedules"] >= 1000
    assert data["elapsed_s"] <= 120
    names = {row["name"] for row in data["scenarios"]}
    assert {"agree-ok", "rollback-ack", "file-boot-stale",
            "file-relaunch", "resize-during-rollback",
            "crash-during-resize", "rejoin-stale-token",
            "router-failover", "rejoin-stale-incarnation",
            "wal-replay-vs-live-delta"} <= names
    # file-transport scenarios ran the REAL FileTransport
    assert all(row["schedules"] > 0 for row in data["scenarios"])
    # truncation, if any, is recorded — never silent
    assert set(data["truncated"]) <= names
