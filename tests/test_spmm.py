"""Unit tests: sparse aggregation vs dense reference on tiny random graphs
(SURVEY §4 implication (a))."""

import jax.numpy as jnp
import numpy as np
import pytest

from bnsgcn_tpu.data.graph import synthetic_graph
from bnsgcn_tpu.ops.spmm import agg_mean, agg_sum, gather_scatter_sum, segment_softmax


def test_agg_sum_matches_dense():
    g = synthetic_graph(n_nodes=50, avg_degree=6, n_feat=8, seed=1)
    h = np.asarray(g.feat, dtype=np.float32)
    out = np.asarray(agg_sum(jnp.asarray(h), jnp.asarray(g.src, jnp.int32),
                             jnp.asarray(g.dst, jnp.int32), g.n_nodes))
    expect = g.dense_adj() @ h
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)


def test_agg_sum_padded_edges_land_in_trash():
    g = synthetic_graph(n_nodes=30, avg_degree=4, n_feat=4, seed=2)
    src = np.concatenate([g.src, np.zeros(7, np.int64)])
    dst = np.concatenate([g.dst, np.full(7, g.n_nodes, np.int64)])  # trash row
    out = np.asarray(agg_sum(jnp.asarray(g.feat), jnp.asarray(src, jnp.int32),
                             jnp.asarray(dst, jnp.int32), g.n_nodes))
    expect = g.dense_adj() @ np.asarray(g.feat)
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("chunk", [16, 64])
def test_agg_sum_chunked_matches_unchunked(chunk):
    g = synthetic_graph(n_nodes=40, avg_degree=8, n_feat=8, seed=3)
    e = g.n_edges
    pad = (-e) % chunk
    src = np.concatenate([g.src, np.zeros(pad, np.int64)])
    dst = np.concatenate([g.dst, np.full(pad, g.n_nodes, np.int64)])
    a = gather_scatter_sum(jnp.asarray(g.feat), jnp.asarray(src, jnp.int32),
                           jnp.asarray(dst, jnp.int32), g.n_nodes, edge_chunk=chunk)
    b = gather_scatter_sum(jnp.asarray(g.feat), jnp.asarray(src, jnp.int32),
                           jnp.asarray(dst, jnp.int32), g.n_nodes, edge_chunk=0)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_agg_mean_uses_provided_degree():
    g = synthetic_graph(n_nodes=25, avg_degree=5, n_feat=3, seed=4)
    in_deg = g.in_degrees().astype(np.float32)
    out = np.asarray(agg_mean(jnp.asarray(g.feat), jnp.asarray(g.src, jnp.int32),
                              jnp.asarray(g.dst, jnp.int32), g.n_nodes,
                              jnp.asarray(in_deg)))
    expect = (g.dense_adj() @ np.asarray(g.feat)) / in_deg[:, None]
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)


def test_segment_softmax_matches_dense():
    rng = np.random.default_rng(5)
    n, e, heads = 10, 40, 2
    dst = rng.integers(0, n, e)
    scores = rng.normal(size=(e, heads)).astype(np.float32)
    out = np.asarray(segment_softmax(jnp.asarray(scores), jnp.asarray(dst, jnp.int32), n))
    for v in range(n):
        sel = dst == v
        if sel.sum() == 0:
            continue
        ex = np.exp(scores[sel] - scores[sel].max(0))
        np.testing.assert_allclose(out[sel], ex / ex.sum(0), rtol=1e-5, atol=1e-6)
    # per-dst sums are 1
    sums = np.zeros((n, heads))
    np.add.at(sums, dst, out)
    present = np.isin(np.arange(n), dst)
    np.testing.assert_allclose(sums[present], 1.0, rtol=1e-5)


def test_segment_softmax_mask_removes_edges():
    rng = np.random.default_rng(6)
    n, e = 6, 20
    dst = rng.integers(0, n, e)
    scores = rng.normal(size=(e, 1)).astype(np.float32)
    mask = rng.random(e) < 0.5
    out = np.asarray(segment_softmax(jnp.asarray(scores), jnp.asarray(dst, jnp.int32),
                                     n, mask=jnp.asarray(mask)))
    assert np.all(out[~mask] == 0.0)
    sums = np.zeros((n, 1))
    np.add.at(sums, dst, out)
    for v in range(n):
        if mask[dst == v].sum() > 0:
            np.testing.assert_allclose(sums[v], 1.0, rtol=1e-5)
        else:
            np.testing.assert_allclose(sums[v], 0.0, atol=1e-7)
