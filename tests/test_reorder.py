"""--reorder exactness (data/reorder.py) + the layout fast-path pins.

The tentpole invariant: the reorder pass permutes each part's REAL inner
rows once, at load time, and is invisible at every user-visible edge —
gather_parts maps results back through the permuted global_nid, so the
global-order logits of a `--reorder cluster` run are BITWISE equal to
`--reorder off` for the pure-ELL and segment SpMMs (per-row sums see the
same sources in the same slot order) and reassociation-close for the
hybrid (the dense/residual split moves with the row order). Pinned here
across all three halo strategies at rate 1.0, composed with --overlap
split and a replicas x parts x feat mesh, plus:

* apply_reorder invariants: per-part bijection, identity on padding rows,
  global-id edge multiset exactly preserved, shapes/n_b/degree multisets
  unchanged, ValueError on multi-host partial artifacts;
* the permutation disk cache: memoized on second load, keyed on tile so
  t256/t512 orders never alias, stale shapes rebuilt, no path w/o
  --cache-dir;
* layout-cache key audit: hybrid/ell/gat keys over tile x overlap x
  reorder are pairwise distinct (the t256-vs-t512 aliasing regression);
* coverage really rises where it should: a community SBM whose node ids
  were scrambled recovers >= +15 points of dense-tile coverage;
* the bincount/packed-sort layout builders (BNSGCN_LAYOUT_FASTPATH=1,
  the default) are bitwise identical to the legacy np.unique/argsort
  passes on all three layout families, raw and reordered;
* e2e through the real CLI: `--reorder cluster --halo-refresh 2` runs the
  header/obs plumbing ('+ro' halo label, reorder + layout_build events),
  and the default pipeline is bitwise `--reorder off`.
"""

import dataclasses
import os
import re
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import Graph, sbm_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.data.reorder import (REORDER_ALGO, apply_reorder,
                                     artifact_coverage, compute_orders,
                                     maybe_reorder, reorder_cache_path)
from bnsgcn_tpu.evaluate import gather_parts
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.ops.block_spmm import effective_occupancy
from bnsgcn_tpu.parallel import feat as feat_mod
from bnsgcn_tpu.parallel.mesh import make_parts_mesh
from bnsgcn_tpu.parallel.replicas import make_mesh
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                ell_layout_key, gat_layout_key,
                                hybrid_layout_key, init_training,
                                place_blocks, place_replicated)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------------
# fixtures: a skew-partitioned graph and its reordered twin
# ----------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ro4():
    """4-part skewed partition + the same artifacts reordered with a small
    tile_r (32) so the LPA clustering + FFD packing path really runs at
    this size instead of degenerating to one degree-sorted cluster."""
    g = synthetic_graph(n_nodes=160, avg_degree=7, n_feat=6, seed=43,
                        power_law=True)
    pid = np.zeros(g.n_nodes, dtype=np.int32)
    pid[80:120] = 1
    pid[120:144] = 2
    pid[144:] = 3
    art = build_artifacts(g, pid)
    orders = compute_orders(art, tile_r=32)
    # the permutation must be non-trivial or every test below is vacuous
    assert any((orders[p] != np.arange(art.pad_inner)).any() for p in range(4))
    return g, art, apply_reorder(art, orders), orders, make_parts_mesh(4)


def _train(g, art, mesh, reorder, *, spmm="ell", strategy="padded",
           overlap="off", epochs=2):
    """Forward logits (global node order, via gather_parts) + train losses
    for one (artifact, resolved-reorder) pair. rate 1.0 and dropout 0.0:
    BNS sampling and dropout draws are row-position-keyed, so any rate < 1
    would select different nodes under the permutation by design."""
    cfg = Config(model="graphsage", dropout=0.0, use_pp=False, norm="layer",
                 n_train=g.n_train, lr=0.01, sampling_rate=1.0, spmm=spmm,
                 halo_exchange=strategy, overlap=overlap, reorder=reorder,
                 n_partitions=mesh.devices.size, n_feat=g.n_feat,
                 n_class=g.n_class)
    spec = ModelSpec("graphsage", (g.n_feat, 16, g.n_class), norm="layer",
                     dropout=0.0, train_size=g.n_train)
    fns, _, tables, _ = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, "graphsage")
    blk_np.update(fns.extra_blk)
    for k in fns.drop_blk_keys:
        blk_np.pop(k, None)
    blk = place_blocks(blk_np, mesh)
    tb = place_replicated(tables, mesh)
    params, state = init_params(jax.random.key(5), spec)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh)
    logits = fns.forward(params, state, jnp.uint32(2), blk, tb,
                         jax.random.key(0))
    losses = []
    for e in range(epochs):
        params, state, opt, loss = fns.train_step(
            params, state, opt, jnp.uint32(e), blk, tb,
            jax.random.key(0), jax.random.key(1))
        losses.append(float(loss))
    return gather_parts(art, np.asarray(logits)), losses, fns.overlap


# ----------------------------------------------------------------------------
# round-trip exactness: permuted-space run == off after the inverse map
# ----------------------------------------------------------------------------

@pytest.mark.quickgate
@pytest.mark.parametrize("strategy", ["padded", "shift", "ragged"])
def test_ell_logits_bitwise_under_reorder(ro4, strategy):
    """The acceptance pin: per-row ELL sums see the same sources in the
    same slot order (stable dst grouping of the same edge sequence), so
    global-order logits are bitwise invariant under the permutation for
    EVERY halo strategy."""
    g, art, art_ro, _, mesh = ro4
    lo, losses_o, _ = _train(g, art, mesh, "off", strategy=strategy)
    lr, losses_r, _ = _train(g, art_ro, mesh, "cluster", strategy=strategy)
    assert np.array_equal(lo, lr), strategy
    for a, b in zip(losses_o, losses_r):
        assert abs(a - b) <= 1e-6 * max(abs(a), 1.0), (strategy, losses_o,
                                                       losses_r)


def test_segment_logits_bitwise_under_reorder(ro4):
    g, art, art_ro, _, mesh = ro4
    lo, _, _ = _train(g, art, mesh, "off", spmm="segment")
    lr, _, _ = _train(g, art_ro, mesh, "cluster", spmm="segment")
    assert np.array_equal(lo, lr)


def test_hybrid_logits_allclose_under_reorder(ro4):
    """The hybrid's dense/residual split moves with the row order (that's
    the point), so per-row sums reassociate: allclose, not bitwise."""
    g, art, art_ro, _, mesh = ro4
    lo, losses_o, _ = _train(g, art, mesh, "off", spmm="hybrid")
    lr, losses_r, _ = _train(g, art_ro, mesh, "cluster", spmm="hybrid")
    scale = np.abs(lo).max() + 1e-9
    assert np.abs(lr - lo).max() / scale < 1e-5
    for a, b in zip(losses_o, losses_r):
        assert abs(a - b) <= 1e-4 * max(abs(a), 1.0)


def test_composes_with_overlap_split(ro4):
    """--overlap split re-derives interior/frontier membership from the
    permuted artifacts; frontier-ness is a per-row property that travels
    with its row, so the split path stays bitwise too."""
    g, art, art_ro, _, mesh = ro4
    lo, losses_o, ov_o = _train(g, art, mesh, "off", overlap="split")
    lr, losses_r, ov_r = _train(g, art_ro, mesh, "cluster", overlap="split")
    assert ov_o == ov_r == "split"      # both really ran the split path
    assert np.array_equal(lo, lr)
    for a, b in zip(losses_o, losses_r):
        assert abs(a - b) <= 1e-6 * max(abs(a), 1.0)


def test_composes_with_replicas_and_feat_mesh():
    """2 x 2 x 2 replicas x parts x feat: the fused loss/grad on permuted
    artifacts matches the raw-artifact run — the reorder changes no
    estimator on any mesh shape."""
    g = synthetic_graph(n_nodes=120, avg_degree=6, n_feat=6, seed=44,
                        power_law=True)
    pid = (np.arange(g.n_nodes) >= 70).astype(np.int32)
    art = build_artifacts(g, pid)
    art_ro = apply_reorder(art, compute_orders(art, tile_r=32))
    mesh = make_mesh(2, 2, 2)

    def run(a, reorder):
        cfg = Config(model="graphsage", dropout=0.0, use_pp=False,
                     norm="layer", n_train=g.n_train, lr=0.01,
                     sampling_rate=1.0, spmm="ell", reorder=reorder,
                     replicas=2, feat=2, n_partitions=2, n_feat=g.n_feat,
                     n_class=g.n_class)
        spec = ModelSpec("graphsage", (g.n_feat, 16, g.n_class),
                         norm="layer", dropout=0.0, train_size=g.n_train)
        fns, _, tables, _ = build_step_fns(cfg, spec, a, mesh)
        assert fns.n_replicas == 2 and fns.n_feat == 2
        blk_np = build_block_arrays(a, "graphsage")
        blk_np.update(fns.extra_blk)
        blk = place_blocks(blk_np, mesh)
        tb = place_replicated(tables, mesh)
        params, state = init_params(jax.random.key(5), spec)
        params_np = jax.tree.map(np.asarray, params)
        p = feat_mod.place_params(params_np, mesh, spec)
        s = place_replicated(state, mesh)
        loss, grads = fns.loss_and_grad(p, s, jnp.uint32(0), blk, tb,
                                        jax.random.key(0), jax.random.key(1))
        return float(loss), jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), grads)

    lo, go = run(art, "off")
    lr, gr = run(art_ro, "cluster")
    assert abs(lr - lo) <= 1e-5 * max(abs(lo), 1.0)
    for a, b in zip(jax.tree.leaves(go), jax.tree.leaves(gr)):
        s = np.abs(a).max() + 1e-9
        assert np.abs(b - a).max() / s < 1e-4


# ----------------------------------------------------------------------------
# apply_reorder invariants
# ----------------------------------------------------------------------------

def _global_edge_keys(a, p):
    """Edge multiset of part p in GLOBAL ids: inner endpoints through the
    (permuted) global_nid, halo sources by their (untouched) slot id, the
    pad_inner trash row as -1. Sorted => order-free comparison."""
    gn = a.global_nid[p].astype(np.int64)
    s = a.src[p].astype(np.int64)
    d = a.dst[p].astype(np.int64)
    gs = np.where(s < a.pad_inner, gn[np.minimum(s, a.pad_inner - 1)],
                  10**9 + s)
    gd = np.where(d < a.pad_inner, gn[np.minimum(d, a.pad_inner - 1)], -1)
    return np.sort((gs + 2) * np.int64(10**10) + (gd + 2))


def test_apply_reorder_invariants(ro4):
    g, art, art_ro, orders, _ = ro4
    P = art.feat.shape[0]
    for p in range(P):
        n_i = int(art.n_inner[p])
        # bijection on the inner rows, identity on padding rows
        assert np.array_equal(np.sort(orders[p][:n_i]), np.arange(n_i))
        assert np.array_equal(orders[p][n_i:],
                              np.arange(n_i, art.pad_inner))
    # geometry unchanged: shapes, pads, boundary counts, degree multisets
    assert art_ro.pad_inner == art.pad_inner
    assert art_ro.pad_boundary == art.pad_boundary
    assert np.array_equal(art_ro.n_b, art.n_b)
    assert np.array_equal(art_ro.n_inner, art.n_inner)
    for p in range(P):
        assert np.array_equal(np.sort(art_ro.in_deg[p]),
                              np.sort(art.in_deg[p]))
        # every (node, label) pair travels with its row
        a = dict(zip(art.global_nid[p][art.inner_mask[p]].tolist(),
                     art.label[p][art.inner_mask[p]].tolist()))
        b = dict(zip(art_ro.global_nid[p][art_ro.inner_mask[p]].tolist(),
                     art_ro.label[p][art_ro.inner_mask[p]].tolist()))
        assert a == b
        # the edge multiset in global ids is exactly preserved
        assert np.array_equal(_global_edge_keys(art, p),
                              _global_edge_keys(art_ro, p))
    # multi-host partial loads must be refused, not silently half-permuted
    partial = dataclasses.replace(art, feat=art.feat[:1])
    with pytest.raises(ValueError, match="full artifacts"):
        apply_reorder(partial, orders[:1])


# ----------------------------------------------------------------------------
# permutation disk cache + layout-cache key audit
# ----------------------------------------------------------------------------

def test_reorder_cache_memoizes_and_keys_on_tile(ro4, tmp_path):
    _, art, _, _, _ = ro4
    cfg = Config(reorder="cluster", cache_dir=str(tmp_path),
                 graph_name="rotest")
    p512 = reorder_cache_path(cfg, art, 512)
    p256 = reorder_cache_path(cfg, art, 256)
    assert p512 != p256, "t256 and t512 orders must never alias"
    assert REORDER_ALGO in p512 and p512.endswith("_t512.pkl")
    assert reorder_cache_path(cfg.replace(cache_dir=""), art, 512) is None

    quiet = lambda *a: None                                   # noqa: E731
    a1, r1, i1 = maybe_reorder(cfg, art, log=quiet)
    assert r1 == "cluster" and i1["cached"] is False
    assert os.path.exists(p512)
    a2, _, i2 = maybe_reorder(cfg, art, log=quiet)
    assert i2["cached"] is True
    np.testing.assert_array_equal(a1.global_nid, a2.global_nid)
    np.testing.assert_array_equal(a1.dst, a2.dst)
    # a stale (wrong-shape) cached order is rebuilt, never half-applied
    from bnsgcn_tpu.utils.diskcache import atomic_dump
    atomic_dump(np.zeros((2, 3), np.int64), p512)
    a3, _, i3 = maybe_reorder(cfg, art, log=quiet)
    assert i3["cached"] is False
    np.testing.assert_array_equal(a3.global_nid, a1.global_nid)
    # off is the untouched pre-PR pipeline: same object, no work, no event
    a4, r4, i4 = maybe_reorder(cfg.replace(reorder="off"), art, log=quiet)
    assert a4 is art and r4 == "off" and i4 == {}


def test_layout_keys_never_alias():
    """The satellite key audit: every (tile, overlap, reorder) combination
    gets its own hybrid/ell/gat layout-cache key — a t256 layout can never
    be served a t512 pickle, nor a reordered build a raw one."""
    keys, n = set(), 0
    for tile in (512, 256):
        for overlap in ("off", "split"):
            for ro in ("off", "cluster"):
                keys.add(hybrid_layout_key(Config(
                    block_tile=tile, overlap=overlap, reorder=ro)))
                n += 1
    for overlap in ("off", "split"):
        for ro in ("off", "cluster"):
            keys.add(ell_layout_key(Config(overlap=overlap, reorder=ro)))
            n += 1
    for ro in ("off", "cluster"):
        keys.add(gat_layout_key(Config(reorder=ro)))
        n += 1
    assert len(keys) == n, sorted(keys)
    # auto occupancy and its resolved explicit value still share one entry
    occ = effective_occupancy(0, 512, 512)
    assert (hybrid_layout_key(Config(block_occupancy=0))
            == hybrid_layout_key(Config(block_occupancy=occ)))


# ----------------------------------------------------------------------------
# coverage really rises: scrambled community SBM
# ----------------------------------------------------------------------------

@pytest.fixture(scope="module")
def scrambled_sbm():
    """32-community SBM whose node ids were randomly relabeled — the
    worst case the reorder pass exists for: structure present, order
    destroyed (identity t256 coverage ~18%)."""
    gs = sbm_graph(n_nodes=8192, n_class=32, n_feat=8, p_in=0.008,
                   p_out=0.0001, seed=3)
    rng = np.random.default_rng(0)
    perm = rng.permutation(gs.n_nodes)
    inv = np.argsort(perm)
    g2 = Graph(gs.n_nodes, perm[gs.src], perm[gs.dst], gs.feat[inv],
               gs.label[inv], gs.train_mask[inv], gs.val_mask[inv],
               gs.test_mask[inv])
    return build_artifacts(g2, partition_graph(g2, 1, method="random",
                                               seed=0))


def test_reorder_recovers_scrambled_communities(scrambled_sbm):
    art = scrambled_sbm
    occ = effective_occupancy(0, 256, 256)
    budget = 2048 << 20
    before = artifact_coverage(art, occ, budget, 256)
    art_ro = apply_reorder(art, compute_orders(art, tile_r=256))
    after = artifact_coverage(art_ro, occ, budget, 256)
    # measured 0.18 -> 0.45; pin a generous floor, not the exact number
    assert after >= before + 0.15, (before, after)


def test_auto_declines_when_ldg_baseline_wins(scrambled_sbm):
    """auto's baseline is what --reorder off ACTUALLY builds with — the
    hybrid's per-build LDG cluster_order — not the raw load order. On the
    scrambled SBM the LDG recovers the communities better than the LPA
    pass (measured 0.59 vs 0.45), so auto must keep the off path."""
    cfg = Config(reorder="auto", block_tile=256)
    art2, resolved, info = maybe_reorder(cfg, scrambled_sbm,
                                         log=lambda *a: None)
    assert resolved == "off"
    assert info["coverage_after"] <= info["coverage_before"]
    assert art2 is scrambled_sbm
    # cluster mode applies unconditionally — the A/B lever stays available
    art3, r3, _ = maybe_reorder(cfg.replace(reorder="cluster"),
                                scrambled_sbm, log=lambda *a: None)
    assert r3 == "cluster" and art3 is not scrambled_sbm


def test_auto_applies_in_the_skew_only_regime():
    """Structure-free power-law (the uniform bench regime, where LDG
    scrambles the one exploitable signal — popularity skew): auto applies
    (measured t256 coverage 0.50 LDG -> 0.56 reorder at this size)."""
    g = synthetic_graph(n_nodes=8192, avg_degree=12, n_feat=8, seed=7,
                        power_law=True)
    art = build_artifacts(g, partition_graph(g, 1, method="random", seed=0))
    cfg = Config(reorder="auto", block_tile=256)
    art2, resolved, info = maybe_reorder(cfg, art, log=lambda *a: None)
    assert resolved == "cluster"
    assert info["coverage_after"] > info["coverage_before"]
    assert art2 is not art


# ----------------------------------------------------------------------------
# layout fast paths: bitwise == the legacy np.unique/argsort builders
# ----------------------------------------------------------------------------

def _assert_same(a, b, path=""):
    if isinstance(a, np.ndarray):
        assert np.array_equal(a, np.asarray(b)), path
    elif isinstance(a, dict):
        assert sorted(a) == sorted(b), path
        for k in a:
            _assert_same(a[k], b[k], f"{path}.{k}")
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_same(x, y, f"{path}[{i}]")
    elif dataclasses.is_dataclass(a) and not isinstance(a, type):
        for f in dataclasses.fields(a):
            _assert_same(getattr(a, f.name), getattr(b, f.name),
                         f"{path}.{f.name}")
    else:
        assert a == b, path


def test_grouped_order_matches_stable_argsort(monkeypatch):
    from bnsgcn_tpu.ops.ell import grouped_order
    rng = np.random.default_rng(0)
    cases = [
        (np.zeros(0, np.int64), 4),
        (np.zeros(1, np.int64), 1),
        (rng.integers(0, 7, 5000).astype(np.int64), 7),       # heavy ties
        (np.repeat(np.arange(50), 100).astype(np.int64), 50),  # all runs
        (rng.permutation(4096).astype(np.int64), 4096),        # no ties
    ]
    for keys, n_keys in cases:
        monkeypatch.setenv("BNSGCN_LAYOUT_FASTPATH", "1")
        fast = grouped_order(keys, n_keys)
        monkeypatch.setenv("BNSGCN_LAYOUT_FASTPATH", "0")
        legacy = grouped_order(keys, n_keys)
        np.testing.assert_array_equal(fast, legacy)
        np.testing.assert_array_equal(legacy,
                                      np.argsort(keys, kind="stable"))


def test_fastpath_builders_bitwise_equal_legacy(ro4, monkeypatch):
    """All three layout families (pure ELL, split ELL, hybrid) + the
    coverage estimator, built on raw AND reordered artifacts, with the
    fast paths on vs. the legacy passes: every array bitwise equal."""
    from bnsgcn_tpu.ops import block_spmm as bs
    from bnsgcn_tpu.ops import ell as ell_mod
    _, art, art_ro, _, _ = ro4
    P = art.src.shape[0]
    results = {}
    for env in ("1", "0"):
        monkeypatch.setenv("BNSGCN_LAYOUT_FASTPATH", env)
        for name, a in (("raw", art), ("ro", art_ro)):
            pi = np.stack([bs.cluster_order(a.src[p], a.dst[p], a.pad_inner,
                                            a.n_ext)[0] for p in range(P)])
            pe = np.concatenate(
                [pi, np.tile(np.arange(a.pad_inner, a.n_ext), (P, 1))],
                axis=1)
            results[env, name, "ell"] = ell_mod.build_layouts(
                a.src, a.dst, a.pad_inner, a.n_ext)
            results[env, name, "split"] = ell_mod.build_split_layouts(
                a.src, a.dst, a.pad_inner, a.n_ext)
            results[env, name, "hyb"] = bs.build_block_layouts(
                a.src, a.dst, a.pad_inner, a.n_ext, pi, pe,
                occupancy_min=16, tile_r=64, tile_c=64)
            real = a.dst[0] < a.pad_inner
            results[env, name, "cov"] = bs.estimate_coverage(
                pi[0], pe[0], a.pad_inner, a.n_ext, a.dst[0][real],
                a.src[0][real], occupancy_min=16,
                tile_budget_bytes=2048 << 20, tile_r=64, tile_c=64)
    for name in ("raw", "ro"):
        for fam in ("ell", "split", "hyb", "cov"):
            _assert_same(results["1", name, fam], results["0", name, fam],
                         f"{name}/{fam}")


# ----------------------------------------------------------------------------
# e2e through the real CLI
# ----------------------------------------------------------------------------

E2E_ARGS = [
    "--dataset", "sbm", "--partition-method", "random", "--n-partitions",
    "2", "--model", "graphsage", "--n-layers", "2", "--n-hidden", "8",
    "--sampling-rate", "1.0", "--n-epochs", "6", "--log-every", "2",
    "--no-eval", "--no-comm-trace", "--fix-seed", "--seed", "11",
]


def _run_main(tmp_path, extra=()):
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               PYTHONPATH=REPO)
    cmd = ([sys.executable, "-m", "bnsgcn_tpu.main"] + E2E_ARGS
           + ["--part-path", str(tmp_path / "parts"),
              "--results-path", str(tmp_path / "res")] + list(extra))
    return subprocess.run(cmd, capture_output=True, text=True, timeout=300,
                          cwd=REPO, env=env)


def _final_loss(out: str) -> str:
    m = re.search(r"RESULT final_loss=(\S+)", out)
    assert m, f"no RESULT line in output:\n{out[-2000:]}"
    return m.group(1)       # string compare == bitwise pin


@pytest.mark.quickgate
def test_e2e_cluster_run_header_and_obs(tmp_path):
    """`--reorder cluster --halo-refresh 2` through the real CLI: the run
    header carries the resolved mode and the '+ro' halo label, and the obs
    log carries the reorder lifecycle event plus per-stage layout_build
    timings (the satellite obs plumbing, end to end)."""
    log = str(tmp_path / "obs.jsonl")
    r = _run_main(tmp_path, ["--reorder", "cluster", "--halo-refresh", "2",
                             "--obs-log", log])
    assert r.returncode == 0, r.stdout + r.stderr
    assert re.search(r"reorder: cluster -> cluster \[lpa-ffd, t512\]",
                     r.stdout), r.stdout[-3000:]
    assert "+ro" in r.stdout            # halo label, e.g. padded+hr2+ro

    from bnsgcn_tpu.obs import load_events
    evs = load_events(log)
    hdr = [e for e in evs if e["kind"] == "run_header"]
    assert hdr and hdr[0]["config"]["reorder"] == "cluster"
    assert "+ro" in hdr[0]["halo"]
    ro = [e for e in evs if e["kind"] == "reorder"]
    assert len(ro) == 1 and ro[0]["resolved"] == "cluster"
    assert ro[0]["algorithm"] == REORDER_ALGO and ro[0]["tile"] == 512
    lb = [e for e in evs if e["kind"] == "layout_build"]
    assert lb and all("stage" in e and e["ms"] >= 0 for e in lb)


def test_e2e_default_is_bitwise_reorder_off(tmp_path):
    """--reorder off is the pre-PR pipeline, pinned bitwise: an untouched
    default run and an explicit `--reorder off` run produce the same final
    loss string, and neither prints a reorder line."""
    a = _run_main(tmp_path)
    assert a.returncode == 0, a.stdout + a.stderr
    b = _run_main(tmp_path, ["--reorder", "off"])
    assert b.returncode == 0, b.stdout + b.stderr
    assert _final_loss(a.stdout) == _final_loss(b.stdout)
    assert "reorder:" not in a.stdout and "reorder:" not in b.stdout
