"""Multi-host coordinated resilience through the real CLI: a genuine
2-process fault matrix on the CPU container.

jaxlib's CPU client refuses cross-process XLA collectives here (the 4
test_multihost.py env-skips), but the rank coordinator needs none: with
`--coord-rank/--coord-world` each process runs the full single-host trainer
(same seed => bit-identical replicated state, the property a real pod's
replicated loss/params give for free) coupled only through the out-of-band
coordinator — so every multi-host recovery path PR 4 could only exercise
single-host runs here as real processes with real exit codes:

* partial SIGTERM (one rank) -> BOTH ranks agree, checkpoint, exit 75, and
  `--resume` reproduces the uninterrupted final loss bit-for-bit;
* NaN on one rank -> coordinated rollback: both ranks restore the same
  checkpoint epoch with the same retry nonce, final losses bitwise equal
  each other AND the single-host rollback of the same fault;
* a hung rank -> the healthy rank's coordinator exchange times out, dumps
  peer liveness naming the straggler, and exits 77;
* a torn local checkpoint copy at resume -> the coordinator ack aborts ALL
  ranks loudly (exit 78) instead of desyncing the epoch schedule.

tools/fault_matrix.sh runs the same stages from the shell.
"""

import os
import re
import shutil
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASE_ARGS = [
    "--dataset", "sbm", "--partition-method", "random", "--n-partitions", "2",
    "--model", "graphsage", "--n-layers", "2", "--n-hidden", "8",
    "--sampling-rate", "0.5", "--use-pp", "--n-epochs", "8",
    "--log-every", "2", "--no-eval", "--no-comm-trace",
    "--fix-seed", "--seed", "11", "--skip-partition",
]


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(extra=None):
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               BNSGCN_RETRY_BACKOFF_S="0", BNSGCN_COORD_TIMEOUT_S="60",
               PYTHONPATH=REPO)
    env.update(extra or {})
    return env


def _prepartition(tmp_path):
    subprocess.run(
        [sys.executable, "-m", "bnsgcn_tpu.partition_cli",
         "--dataset", "sbm", "--partition-method", "random",
         "--n-partitions", "2", "--fix-seed",
         "--part-path", str(tmp_path / "parts")],
        env=_env(), check=True, capture_output=True, cwd=REPO)


def _cmd(tmp_path, ckpt, extra_args=()):
    return ([sys.executable, "-m", "bnsgcn_tpu.main"] + BASE_ARGS
            + ["--part-path", str(tmp_path / "parts"),
               "--ckpt-path", str(ckpt),
               "--results-path", str(tmp_path / "res")]
            + list(extra_args))


def _run_single(tmp_path, ckpt, extra_args=(), timeout=240):
    """One uncoordinated (--coord off) single-host run — the reference."""
    return subprocess.run(
        _cmd(tmp_path, ckpt, ["--coord", "off"] + list(extra_args)),
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=_env())


def _run_pair(tmp_path, ckpts, extra_args=(), rank_env=None, timeout=240):
    """Two coordinated rank processes; returns the CompletedProcess-likes
    [(rc, out), (rc, out)]. `ckpts` is one shared path or a per-rank pair;
    `rank_env` an optional {rank: {env}} overlay."""
    if isinstance(ckpts, (str, os.PathLike)):
        ckpts = (ckpts, ckpts)
    port = _free_port()
    procs = []
    for r in (0, 1):
        cmd = _cmd(tmp_path, ckpts[r],
                   ["--coord", "tcp", "--coord-port", str(port),
                    "--coord-world", "2", "--coord-rank", str(r)]
                   + list(extra_args))
        procs.append(subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO, env=_env((rank_env or {}).get(r))))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return outs


def _final_loss(out: str) -> str:
    m = re.search(r"RESULT final_loss=(\S+)", out)
    assert m, f"no RESULT line in output:\n{out[-2000:]}"
    return m.group(1)       # string compare == bitwise pin


@pytest.mark.quickgate
def test_partial_sigterm_agreed_exit75_and_bitwise_resume(tmp_path):
    """The acceptance pin: SIGTERM injected on rank 1 ONLY -> the agreed
    verdict turns it into a clean all-rank resumable exit 75, and the
    resumed pair reproduces the uninterrupted run's final loss bit-for-bit
    on both ranks (the resumed seed also survives a conflicting --seed)."""
    _prepartition(tmp_path)
    ref = _run_single(tmp_path, tmp_path / "ck_ref")
    assert ref.returncode == 0, ref.stdout[-2000:]
    want = _final_loss(ref.stdout)

    outs = _run_pair(tmp_path, tmp_path / "ck",
                     ["--inject", "sigterm@E3:r1"])
    assert [rc for rc, _ in outs] == [75, 75], outs
    for _, out in outs:
        assert "agreed preemption (requested by rank(s) [1])" in out, out[-2000:]
        assert "resumable checkpoint" in out

    outs = _run_pair(tmp_path, tmp_path / "ck", ["--resume", "--seed", "999"])
    assert [rc for rc, _ in outs] == [0, 0], outs
    for _, out in outs:
        assert "Resumed (agreed via coordinator)" in out, out[-2000:]
        assert _final_loss(out) == want


def test_coordinated_nan_rollback_same_epoch_same_nonce(tmp_path):
    """NaN poisoned on rank 0 only: the agreed verdict rolls BOTH ranks back
    to the same checkpoint epoch with the same retry nonce, and the healed
    pair's final loss is bitwise equal the single-host rollback of the same
    fault — coordination changes who decides, never the numbers."""
    _prepartition(tmp_path)
    single = _run_single(tmp_path, tmp_path / "ck_one", ["--inject", "nan@E5"])
    assert single.returncode == 0, single.stdout[-2000:]
    assert "rolled back to" in single.stdout
    want = _final_loss(single.stdout)

    outs = _run_pair(tmp_path, tmp_path / "ck", ["--inject", "nan@E5:r0"])
    assert [rc for rc, _ in outs] == [0, 0], outs
    assert ("agreed rollback to" in outs[0][1]
            and "restarting all ranks at epoch 4 with retry-nonce 1"
            in outs[0][1]), outs[0][1][-2000:]
    assert ("agreed rollback (decided by rank 0): epoch 5 -> restart 4"
            in outs[1][1] and "retry-nonce 1" in outs[1][1]), outs[1][1][-2000:]
    assert _final_loss(outs[0][1]) == _final_loss(outs[1][1]) == want


def test_coordinator_timeout_exits_77_with_peer_liveness(tmp_path):
    """Rank 1 hangs mid-step: rank 0's verdict exchange must time out
    within the bounded deadline, dump the peer-liveness table naming the
    rank that stalled (one epoch behind), and exit 77; the hung rank's own
    watchdog also exits 77 — no process is ever left hanging forever."""
    _prepartition(tmp_path)
    outs = _run_pair(
        tmp_path, tmp_path / "ck", ["--inject", "hang@E3:r1"],
        rank_env={
            # rank 0 is healthy: only its coordinator deadline may fire
            0: {"BNSGCN_COORD_TIMEOUT_S": "6",
                "BNSGCN_WATCHDOG_MIN_S": "120",
                "BNSGCN_WATCHDOG_GRACE_S": "120"},
            # rank 1 is the hung one: its in-process watchdog fires
            1: {"BNSGCN_COORD_TIMEOUT_S": "6",
                "BNSGCN_WATCHDOG_MIN_S": "2", "BNSGCN_WATCHDOG_FACTOR": "2",
                "BNSGCN_WATCHDOG_GRACE_S": "120"},
        }, timeout=300)
    assert [rc for rc, _ in outs] == [77, 77], outs
    r0 = outs[0][1]
    assert "timed out" in r0 and "rank 1's epoch-3 verdict" in r0, r0[-2000:]
    assert "peer liveness" in r0 and "rank 1: step hb" in r0
    assert "(epoch 2)" in r0            # the straggler is one epoch behind
    assert "[watchdog] step hung" in outs[1][1]


def test_torn_local_checkpoint_copy_aborts_resume_on_all_ranks(tmp_path):
    """Rank-consistent recovery (satellite bugfix): rank 0 broadcasts its
    checkpoint CHOICE and every rank must ack loading it. Rank 1's local
    copy of the chosen file is torn -> the resume aborts loudly on BOTH
    ranks (exit 78) naming the rank and the file, instead of rank 1
    silently walking to an older epoch or failing mid-epoch."""
    _prepartition(tmp_path)
    outs = _run_pair(tmp_path, tmp_path / "ck", ["--inject", "sigterm@E5"])
    assert [rc for rc, _ in outs] == [75, 75], outs

    # rank 1 gets its own (rsync'd-local-disk style) copy, newest file torn
    shutil.copytree(tmp_path / "ck", tmp_path / "ck_r1")
    from bnsgcn_tpu.resilience import corrupt_file
    newest = max((tmp_path / "ck_r1").glob("*_5.ckpt"))
    corrupt_file(str(newest))

    outs = _run_pair(tmp_path, (tmp_path / "ck", tmp_path / "ck_r1"),
                     ["--resume"])
    assert [rc for rc, _ in outs] == [78, 78], outs
    for _, out in outs:
        assert "resume aborted by agreement" in out, out[-2000:]
        assert "rank 1:" in out and "_5.ckpt" in out
