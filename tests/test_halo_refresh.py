"""Staleness-bounded halo communication: --halo-refresh K + --halo-mode.

  * make_refresh_spec partitions every boundary list into K residue-class
    chunks whose counts sum back to the full tables, and its steady-state
    wire bytes drop ~K x under every strategy;
  * at K=1 the refresh plan applies bit-identically to the historical plan
    across the strategy x wire matrix (quantized wires within per-block
    scale tolerance — the send pad differs by the lane rounding the partial
    geometry deliberately drops);
  * at rate 1.0 the K staggered chunk exchanges, merged through
    refresh_row_mask, reconstruct the exact full exchange bitwise — the
    "staleness is the ONLY approximation" invariant;
  * the full-refresh train step is bitwise the historical step; the cached
    step's staleness bias at rate 1.0 stays within epsilon of the exact
    trajectory for K in {2, 4}; grad-only still learns the SBM task;
  * the CLI path: `+hrK` run label, run_header peak/steady wire MB,
    duty-cycled per-epoch wire_mb, halo_refresh lifecycle events, and
    bitwise-deterministic rollback (cache invalidation -> full-refresh
    epoch) and resume.

No reference equivalent: the reference (like BNS-GCN) exchanges halos every
epoch; bounded-staleness reuse is a capability upgrade for DCN-crossing
meshes where the per-epoch exchange dominates the step.
"""

import json
import os
import re
import shutil
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bnsgcn_tpu.config import Config, ConfigError, parse_config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import sbm_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.parallel.halo import (halo_apply, make_halo_plan,
                                      make_halo_spec, make_halo_plan_refresh,
                                      make_refresh_spec, refresh_row_mask,
                                      wire_bytes)
from bnsgcn_tpu.parallel.mesh import make_parts_mesh, shard_map
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                init_training, place_blocks, place_replicated)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------------
# geometry units: chunk tables and steady-state bytes
# ----------------------------------------------------------------------------

def _skew_nb():
    rng = np.random.default_rng(7)
    n_b = rng.integers(100, 400, size=(4, 4)).astype(np.int64)
    np.fill_diagonal(n_b, 0)
    return n_b


@pytest.mark.quickgate
def test_refresh_spec_chunk_counts_and_steady_bytes():
    """Per-chunk boundary counts must sum back to the full counts (every
    boundary position lives in exactly one chunk), sends stay nonzero
    wherever the full exchange sends (no permanently-silent pair = no bias),
    and the steady-state wire bytes drop ~K x under every strategy — the
    >= 40% @ K=2 acceptance bar of the PR."""
    n_b = _skew_nb()
    for strategy in ("padded", "shift", "ragged"):
        sp_full, tb_full = make_halo_spec(n_b, 0, 512, 0.5, strategy=strategy)
        full_bytes = wire_bytes(sp_full, 64, 2)
        for K, cap in ((2, 0.60), (4, 0.35)):
            sp_r, tb_r = make_refresh_spec(n_b, 0, 512, 0.5, K,
                                           strategy=strategy)
            nbc = np.asarray(tb_r["n_b"], np.int64)
            assert nbc.shape == (K, 4, 4)
            np.testing.assert_array_equal(nbc.sum(axis=0), n_b)
            s_c = np.asarray(tb_r["send_size"], np.int64)
            full_send = np.asarray(tb_full["send_size"], np.int64)
            # a pair the full exchange serves sends in EVERY chunk with rows
            assert np.all((s_c > 0) == ((nbc > 0) & (full_send[None] > 0)))
            assert sp_r.pad_boundary == sp_full.pad_boundary  # cache layout
            rb = wire_bytes(sp_r, 64, 2)
            assert rb <= cap * full_bytes, (strategy, K, rb, full_bytes)


def test_refresh_spec_exact_rate_sends_whole_chunk():
    n_b = _skew_nb()
    for K in (2, 3):
        _, tb = make_refresh_spec(n_b, 0, 512, 1.0, K)
        np.testing.assert_array_equal(np.asarray(tb["send_size"]),
                                      np.asarray(tb["n_b"]))


def test_refresh_row_mask_partitions_halo_slots():
    sp, _ = make_refresh_spec(_skew_nb(), 0, 512, 0.5, 3)
    masks = [np.asarray(refresh_row_mask(sp, 3, jnp.uint32(e)))
             for e in range(3)]
    assert not (masks[0] & masks[1]).any()          # pairwise disjoint
    assert np.all(masks[0] | masks[1] | masks[2])   # and exhaustive
    # period K: epoch e and e+K refresh the same slots
    np.testing.assert_array_equal(
        masks[1], np.asarray(refresh_row_mask(sp, 3, jnp.uint32(4))))


# ----------------------------------------------------------------------------
# plan equivalence on the real 4-part skewed partition
# ----------------------------------------------------------------------------

def _skewed_art():
    g = synthetic_graph(n_nodes=120, avg_degree=7, n_feat=6, seed=41,
                        power_law=True)
    pid = np.zeros(g.n_nodes, dtype=np.int32)
    pid[60:90] = 1
    pid[90:110] = 2
    pid[110:] = 3
    return build_artifacts(g, pid)


def _apply_plans(art, mesh, feat, make_plan_fns, epoch=3):
    """halo_apply each plan builder inside ONE shard_map; returns the list
    of (h_ext, d_feat) numpy pairs for a sum-of-squares cotangent."""
    base = jax.random.key(42)

    def local(blk, *tables_list):
        b = {k: v[0] for k, v in blk.items()}
        outs = []
        for mk, tb in zip(make_plan_fns, tables_list):
            plan = mk[1](mk[0], tb, b["bnd"], jnp.uint32(epoch), base)

            def loss_fn(h, spec=mk[0], plan=plan):
                hx = halo_apply(spec, plan, h)
                return jnp.sum(hx.astype(jnp.float32) ** 2), hx

            (_, hx), g = jax.value_and_grad(loss_fn, has_aux=True)(b["feat"])
            outs.extend([hx[None], g[None]])
        return tuple(outs)

    n = len(make_plan_fns)
    f = jax.jit(shard_map(local, mesh=mesh,
                          in_specs=(P("parts"),) + (P(),) * n,
                          out_specs=(P("parts"),) * (2 * n)))
    blk = place_blocks({"feat": feat, "bnd": art.bnd}, mesh)
    res = f(blk, *[place_replicated(tb, mesh) for _, _, tb in make_plan_fns])
    return [(np.asarray(res[2 * i]), np.asarray(res[2 * i + 1]))
            for i in range(n)]


@pytest.mark.parametrize("wire", ["native", "bf16", "int8", "fp8"])
@pytest.mark.parametrize("strategy", ["padded", "shift", "ragged"])
def test_k1_refresh_plan_matches_full_plan(strategy, wire):
    """K=1 has a single chunk covering every boundary position: the partial
    plan must reproduce the historical exchange. Native/bf16 wires are
    bitwise (positionwise codecs); int8/fp8 per-block scales see a
    differently-padded send block (the refresh geometry drops the x8 lane
    rounding), so they match within quantization tolerance."""
    art = _skewed_art()
    mesh = make_parts_mesh(4)
    feat = art.feat.astype(np.float32)
    sp_f, tb_f = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary,
                                0.5, strategy=strategy, wire=wire)
    sp_r, tb_r = make_refresh_spec(art.n_b, art.pad_inner, art.pad_boundary,
                                   0.5, 1, strategy=strategy, wire=wire)

    def plan_r(spec, tb, bnd, epoch, key):
        return make_halo_plan_refresh(spec, tb, bnd, epoch, key, 1)

    (hx_f, g_f), (hx_r, g_r) = _apply_plans(
        art, mesh, feat,
        [(sp_f, make_halo_plan, tb_f), (sp_r, plan_r, tb_r)])
    if wire in ("native", "bf16"):
        np.testing.assert_array_equal(hx_r, hx_f)
        np.testing.assert_array_equal(g_r, g_f)
    else:
        scale = np.abs(hx_f).max() + 1e-9
        assert np.abs(hx_r - hx_f).max() / scale < 0.05, (strategy, wire)
        gscale = np.abs(g_f).max() + 1e-9
        assert np.abs(g_r - g_f).max() / gscale < 0.05, (strategy, wire)


@pytest.mark.quickgate
def test_staggered_chunks_reconstruct_exact_exchange():
    """rate 1.0, K=3: running the partial exchange for epochs 0..K-1 and
    merging each result through its refresh_row_mask must reconstruct the
    full exact exchange bitwise — proof that a warm steady-state cache
    differs from per-epoch exchange ONLY through staleness."""
    art = _skewed_art()
    mesh = make_parts_mesh(4)
    feat = art.feat.astype(np.float32)
    K = 3
    sp_f, tb_f = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 1.0)
    sp_r, tb_r = make_refresh_spec(art.n_b, art.pad_inner, art.pad_boundary,
                                   1.0, K)
    base = jax.random.key(42)

    def local(blk, tb_f, tb_r):
        b = {k: v[0] for k, v in blk.items()}
        plan_f = make_halo_plan(sp_f, tb_f, b["bnd"], jnp.uint32(0), base)
        full_tail = halo_apply(sp_f, plan_f, b["feat"])[sp_f.pad_inner:]
        merged = jnp.zeros_like(full_tail)
        for e in range(K):
            plan_e = make_halo_plan_refresh(sp_r, tb_r, b["bnd"],
                                            jnp.uint32(e), base, K)
            tail_e = halo_apply(sp_r, plan_e, b["feat"])[sp_r.pad_inner:]
            mask = refresh_row_mask(sp_r, K, jnp.uint32(e))
            merged = jnp.where(mask[:, None], tail_e, merged)
        return full_tail[None], merged[None]

    f = jax.jit(shard_map(local, mesh=mesh, in_specs=(P("parts"), P(), P()),
                          out_specs=(P("parts"), P("parts"))))
    blk = place_blocks({"feat": feat, "bnd": art.bnd}, mesh)
    full_tail, merged = f(blk, place_replicated(tb_f, mesh),
                          place_replicated(tb_r, mesh))
    np.testing.assert_array_equal(np.asarray(merged), np.asarray(full_tail))


# ----------------------------------------------------------------------------
# train-step level: full-refresh bitwise, staleness bias bound, grad-only
# ----------------------------------------------------------------------------

def _train(g, epochs, force_full_each_epoch=False, **cfg_kw):
    """run.py's step dispatch in miniature: full-refresh step when the cache
    is cold, cached step after. Returns the per-epoch loss trajectory."""
    kw = dict(model="graphsage", dropout=0.0, use_pp=True, norm="layer",
              n_train=g.n_train, lr=0.01, sampling_rate=0.5)
    kw.update(cfg_kw)
    cfg = Config(**kw)
    spec = ModelSpec("graphsage", (8, 16, 4), norm="layer", dropout=0.0,
                     use_pp=True, train_size=g.n_train)
    mesh = make_parts_mesh(4)
    art = build_artifacts(g, partition_graph(g, 4, method="random", seed=2))
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, "graphsage")
    blk_np.update(fns.extra_blk)
    for k in fns.drop_blk_keys:
        blk_np.pop(k, None)
    blk = place_blocks(blk_np, mesh)
    tb = place_replicated(tables, mesh)
    blk["feat"] = fns.precompute(blk, place_replicated(tables_full, mesh))
    params, state = init_params(jax.random.key(5), spec)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh)
    tb_r = (place_replicated(fns.tables_refresh, mesh)
            if fns.tables_refresh is not None else None)
    cache, traj = None, []
    for e in range(epochs):
        if fns.train_step_full is not None:
            if cache is None or force_full_each_epoch:
                params, state, opt, loss, cache = fns.train_step_full(
                    params, state, opt, jnp.uint32(e), blk, tb,
                    jax.random.key(0), jax.random.key(1))
            else:
                params, state, opt, loss, cache = fns.train_step_cached(
                    params, state, opt, jnp.uint32(e), blk, tb_r, cache,
                    jax.random.key(0), jax.random.key(1))
        else:
            params, state, opt, loss = fns.train_step(
                params, state, opt, jnp.uint32(e), blk, tb,
                jax.random.key(0), jax.random.key(1))
        traj.append(float(loss))
    return traj


@pytest.fixture(scope="module")
def sbm4():
    return sbm_graph(n_nodes=240, n_class=4, n_feat=8, p_in=0.08,
                     p_out=0.004, seed=44)


@pytest.mark.quickgate
def test_full_refresh_step_is_bitwise_the_historical_step(sbm4):
    """train_step_full replays the historical exchange geometry (it only
    ADDS cache recording): forced full-refresh every epoch must trace the
    exact historical trajectory bitwise."""
    ref = _train(sbm4, 5)
    full = _train(sbm4, 5, halo_refresh=2, force_full_each_epoch=True)
    assert full == ref, (ref, full)


def test_staleness_bias_bounded_at_exact_rate(sbm4):
    """rate 1.0: staleness is the ONLY approximation K introduces (pinned
    bitwise above/in the merge test), so the K in {2, 4} trajectories must
    land within a small epsilon of the exact run — the PR's stated
    accuracy-within-epsilon acceptance criterion, on the loss it trains."""
    exact = _train(sbm4, 40, sampling_rate=1.0)
    eps = 0.05 * abs(exact[0])
    for K in (2, 4):
        stale = _train(sbm4, 40, sampling_rate=1.0, halo_refresh=K)
        assert stale[-1] < 0.5 * stale[0], f"K={K} did not learn"
        assert abs(stale[-1] - exact[-1]) < eps, (K, exact[-1], stale[-1])


def test_grad_only_converges(sbm4):
    """--halo-mode grad-only drops the activation exchange entirely; the
    gradient all-reduce (the loss psum transpose) alone must still learn
    the SBM task, if to a worse loss than the exchanging run."""
    traj = _train(sbm4, 40, halo_mode="grad-only")
    assert traj[-1] < 0.5 * traj[0], traj[-1]


# ----------------------------------------------------------------------------
# flags + StepFns surface
# ----------------------------------------------------------------------------

def test_config_flags_and_step_fns_surface(sbm4):
    cfg = parse_config(["--halo-refresh", "4", "--halo-mode", "grad-only"])
    assert cfg.halo_refresh == 4 and cfg.halo_mode == "grad-only"
    assert parse_config([]).halo_refresh == 1
    assert parse_config([]).halo_mode == "exchange"

    g = sbm4
    spec = ModelSpec("graphsage", (8, 16, 4), norm="layer", dropout=0.0,
                     use_pp=True, train_size=g.n_train)
    art = build_artifacts(g, partition_graph(g, 4, method="random", seed=2))
    mesh = make_parts_mesh(4)

    def build(**kw):
        c = Config(model="graphsage", dropout=0.0, use_pp=True, norm="layer",
                   n_train=g.n_train, sampling_rate=0.5, **kw)
        return build_step_fns(c, spec, art, mesh)[0]

    with pytest.raises(ConfigError, match="halo-refresh"):
        build(halo_refresh=0)
    with pytest.raises(ConfigError, match="halo-mode"):
        build(halo_mode="nope")
    assert build().train_step_full is None              # K=1: nothing built
    fns = build(halo_refresh=2)
    assert fns.train_step_full is not None
    assert fns.train_step_cached is not None
    assert fns.tables_refresh is not None and fns.halo_refresh == 2
    # grad-only ignores the refresh period (warned): no refresh machinery
    fns = build(halo_refresh=4, halo_mode="grad-only")
    assert fns.halo_mode == "grad-only" and fns.train_step_full is None


# ----------------------------------------------------------------------------
# e2e through the CLI: label, header, duty-cycled wire_mb, determinism
# ----------------------------------------------------------------------------

BASE_ARGS = [
    "--dataset", "sbm", "--partition-method", "random", "--n-partitions", "2",
    "--model", "graphsage", "--n-layers", "2", "--n-hidden", "8",
    "--sampling-rate", "0.5", "--use-pp", "--n-epochs", "8",
    "--log-every", "2", "--no-eval", "--no-comm-trace",
    "--fix-seed", "--seed", "11",
]


def _env(extra=None):
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               BNSGCN_RETRY_BACKOFF_S="0", PYTHONPATH=REPO)
    env.update(extra or {})
    return env


def _run(tmp_path, extra_args=(), timeout=240):
    cmd = ([sys.executable, "-m", "bnsgcn_tpu.main"] + BASE_ARGS
           + ["--part-path", str(tmp_path / "parts"),
              "--ckpt-path", str(tmp_path / "ckpt"),
              "--results-path", str(tmp_path / "res")]
           + list(extra_args))
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=_env())


def _final_loss(stdout: str) -> float:
    m = re.search(r"RESULT final_loss=(\S+)", stdout)
    assert m, f"no RESULT line in output:\n{stdout[-2000:]}"
    return float(m.group(1))


def _load_events(path):
    from bnsgcn_tpu.obs import load_events
    return load_events(path)


@pytest.mark.quickgate
def test_cli_e2e_header_label_and_duty_cycled_wire(tmp_path):
    """--halo-refresh 2 end to end: the run labels itself +hr2, the header
    carries both peak and steady-state MB (steady <= 60% of peak — the
    >= 40% acceptance bar), a halo_refresh lifecycle event marks the cold
    full-refresh epoch, and every steady epoch's wire_mb record ships the
    reduced figure."""
    log = str(tmp_path / "obs.jsonl")
    r = _run(tmp_path, ["--halo-refresh", "2", "--obs-log", log])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "+hr2" in r.stdout, r.stdout[-3000:]
    assert "halo cache: full refresh at epoch 0 (start)" in r.stdout
    evs = _load_events(log)
    hdr = next(e for e in evs if e["kind"] == "run_header")
    assert hdr["halo_refresh"] == 2 and hdr["halo_mode"] == "exchange"
    peak, steady = hdr["wire_mb_per_exchange"], hdr["wire_mb_steady"]
    assert steady <= 0.6 * peak, (steady, peak)
    assert any(e["kind"] == "halo_refresh" and e["reason"] == "start"
               for e in evs)
    ep = [e for e in evs if e["kind"] == "epoch"]
    assert ep, "no epoch records"
    # epoch 0 rebuilt the cache at peak cost; the rest ride the steady rate
    by_epoch = {int(e["epoch"]): e["wire_mb"] for e in ep}
    assert by_epoch[0] == pytest.approx(peak, rel=1e-3)
    for e, mb in by_epoch.items():
        if e > 0:
            assert mb <= 0.6 * peak, (e, mb, peak)
    # the report tool renders it (wire column + lifecycle line)
    rep = subprocess.run([sys.executable, "tools/obs_report.py", log],
                         capture_output=True, text=True, timeout=60,
                         cwd=REPO, env=_env())
    assert rep.returncode == 0, rep.stdout + rep.stderr
    assert "halo refresh: K=2" in rep.stdout
    assert "halo_refresh" in rep.stdout and "wire_mb" in rep.stdout


@pytest.mark.quickgate
def test_rollback_invalidates_cache_and_stays_deterministic(tmp_path):
    """nan@E5 under an active K=2 cache: the rollback must invalidate the
    cache (a full-refresh epoch replays at the restart point — the resumed
    state was saved WITHOUT the cache) and the whole recovery is
    deterministic: two identical runs land bitwise-equal final losses."""
    losses = []
    for i in (0, 1):
        log = str(tmp_path / f"obs{i}.jsonl")
        r = _run(tmp_path, ["--halo-refresh", "2", "--inject", "nan@E5",
                            "--ckpt-path", str(tmp_path / f"ck{i}"),
                            "--obs-log", log])
        assert r.returncode == 0, r.stdout + r.stderr
        assert "rolled back" in r.stdout or "rollback" in r.stdout.lower()
        kinds = [e["kind"] for e in _load_events(log)]
        assert "rollback" in kinds
        # two halo_refresh events: the cold start AND the post-rollback
        # invalidation
        ref = [e for e in _load_events(log) if e["kind"] == "halo_refresh"]
        assert {e["reason"] for e in ref} == {"start", "rollback"}, ref
        losses.append(_final_loss(r.stdout))
    assert losses[0] == losses[1], losses


@pytest.mark.slow
def test_resume_forces_full_refresh_and_is_deterministic(tmp_path):
    """sigterm@E3 under K=2, then --resume twice from copies of the same
    checkpoint: the cache is never checkpointed, so each resume must replay
    a full-refresh epoch (reason=resume) and the two resumed runs must land
    bitwise-identical final losses."""
    interrupted = _run(tmp_path, ["--halo-refresh", "2",
                                  "--inject", "sigterm@E3"])
    assert interrupted.returncode == 75, (
        interrupted.returncode, interrupted.stderr[-2000:])
    losses = []
    for i in (0, 1):
        ck = str(tmp_path / f"ck_resume{i}")
        shutil.copytree(str(tmp_path / "ckpt"), ck)
        r = _run(tmp_path, ["--halo-refresh", "2", "--resume",
                            "--skip-partition", "--ckpt-path", ck])
        assert r.returncode == 0, r.stdout + r.stderr
        assert "Resumed from" in r.stdout
        m = re.search(r"full refresh at epoch (\d+) \(resume\)", r.stdout)
        assert m, r.stdout[-3000:]
        losses.append(_final_loss(r.stdout))
    assert losses[0] == losses[1], losses
