"""Native C++ partitioner: build, invariants, quality, determinism."""

import numpy as np
import pytest

from bnsgcn_tpu.data.graph import sbm_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import edge_cut, random_partition
from bnsgcn_tpu.native import native_available, native_partition

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="no C++ toolchain to build native lib")


@pytest.fixture(scope="module")
def g():
    return sbm_graph(n_nodes=600, n_class=6, n_feat=4, p_in=0.06, p_out=0.002,
                     seed=42)


def test_every_node_assigned_and_balanced(g):
    pid = native_partition(g, 4, obj="cut", seed=0)
    assert pid is not None and pid.shape == (g.n_nodes,)
    assert pid.min() >= 0 and pid.max() < 4
    counts = np.bincount(pid, minlength=4)
    cap = -(-g.n_nodes // 4)
    assert counts.max() <= int(cap * 1.02) + 1
    assert counts.min() > 0


@pytest.mark.parametrize("obj", ["cut", "vol"])
def test_beats_random_partition(g, obj):
    pid_n = native_partition(g, 4, obj=obj, seed=0)
    pid_r = random_partition(g, 4, seed=0)
    # an SBM has community structure: locality partitioner must do much better
    assert edge_cut(g, pid_n) < 0.7 * edge_cut(g, pid_r), (
        edge_cut(g, pid_n), edge_cut(g, pid_r))


def test_deterministic_by_seed(g):
    a = native_partition(g, 3, seed=7)
    b = native_partition(g, 3, seed=7)
    c = native_partition(g, 3, seed=8)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_single_part_and_power_law():
    g2 = synthetic_graph(n_nodes=300, avg_degree=10, n_feat=4, seed=1,
                         power_law=True)
    pid1 = native_partition(g2, 1)
    assert np.all(pid1 == 0)
    pid8 = native_partition(g2, 8, seed=3)
    assert np.bincount(pid8, minlength=8).min() > 0


def test_vol_objective_beats_cut_on_comm_volume():
    """The 'vol' refinement optimizes the TRUE directed comm volume (own +
    neighbor halo-set deltas), so it must beat the 'cut' objective on
    comm_volume — and 'cut' must beat 'vol' on edge_cut (differentiated
    objectives, reference objtype vol|cut, helper/utils.py:94-95)."""
    from bnsgcn_tpu.data.partitioner import comm_volume
    g2 = synthetic_graph(n_nodes=2000, avg_degree=16, n_feat=4, seed=2,
                         power_law=True)
    for P in (4, 8):
        pid_v = native_partition(g2, P, obj="vol", seed=0)
        pid_c = native_partition(g2, P, obj="cut", seed=0)
        assert comm_volume(g2, pid_v) < comm_volume(g2, pid_c), P
        assert edge_cut(g2, pid_c) < edge_cut(g2, pid_v), P


def test_native_comm_volume_matches_python(g):
    from bnsgcn_tpu.data.partitioner import comm_volume
    from bnsgcn_tpu.native import native_comm_volume
    pid = native_partition(g, 4, obj="vol", seed=1)
    assert native_comm_volume(g, pid, 4) == comm_volume(g, pid)


def test_multi_seed_never_worse():
    """Best-of-n_seeds is monotone: the 3-seed result's objective equals the
    min over its three candidates — in multilevel mode that pool is
    [ml(seed0), ml(seed1), flat(seed2)] (the last slot keeps a flat
    candidate so structure-free graphs never regress; seeds advance by the
    golden-ratio stride, matching partitioner.cpp)."""
    from bnsgcn_tpu.data.partitioner import comm_volume
    g2 = synthetic_graph(n_nodes=800, avg_degree=10, n_feat=4, seed=5,
                         power_law=True)
    best = comm_volume(g2, native_partition(g2, 4, obj="vol", seed=0, n_seeds=3))
    stride = 0x9E3779B97F4A7C15
    singles = [comm_volume(g2, native_partition(
        g2, 4, obj="vol", seed=(i * stride) % 2**64, n_seeds=1,
        multilevel=(i < 2)))
        for i in range(3)]
    assert best <= min(singles), (best, singles)
    assert best == min(singles)      # best-of picks one of the candidates


def test_partition_graph_uses_native():
    from bnsgcn_tpu.data.partitioner import partition_graph
    g2 = sbm_graph(n_nodes=400, n_class=4, n_feat=4, seed=9)
    pid = partition_graph(g2, 4, method="metis", obj="cut", seed=0)
    pid_native = native_partition(g2, 4, obj="cut", seed=0)
    np.testing.assert_array_equal(pid, pid_native)
