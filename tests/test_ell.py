"""ELL bucketed SpMM == segment_sum SpMM, forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.ops.ell import build_ell_numpy, build_layouts, make_ell_spmm
from bnsgcn_tpu.ops.spmm import agg_sum


@pytest.mark.parametrize("seed", [0, 1])
def test_ell_single_part_matches_segment(seed):
    g = synthetic_graph(n_nodes=70, avg_degree=7, n_feat=5, seed=seed,
                        power_law=True)
    art = build_artifacts(g, partition_graph(g, 1))
    n_ext = art.pad_inner + art.n_parts * art.pad_boundary
    fwd_spec, bwd_spec, arrays = build_layouts(art.src, art.dst,
                                               art.pad_inner, n_ext)
    spmm = make_ell_spmm(fwd_spec, bwd_spec,
                         len(fwd_spec.widths), len(bwd_spec.widths))
    arrays0 = {k: jnp.asarray(v[0]) for k, v in arrays.items()}
    h = jnp.asarray(np.random.default_rng(seed).normal(
        size=(n_ext, 5)).astype(np.float32))
    out_ell = spmm(arrays0, h)
    out_seg = agg_sum(h, jnp.asarray(art.src[0]), jnp.asarray(art.dst[0]),
                      art.pad_inner)
    np.testing.assert_allclose(np.asarray(out_ell), np.asarray(out_seg),
                               rtol=1e-5, atol=1e-5)


def test_ell_gradient_matches_segment():
    g = synthetic_graph(n_nodes=50, avg_degree=6, n_feat=4, seed=3,
                        power_law=True)
    art = build_artifacts(g, partition_graph(g, 1))
    n_ext = art.pad_inner + art.n_parts * art.pad_boundary
    fwd_spec, bwd_spec, arrays = build_layouts(art.src, art.dst,
                                               art.pad_inner, n_ext)
    spmm = make_ell_spmm(fwd_spec, bwd_spec,
                         len(fwd_spec.widths), len(bwd_spec.widths))
    arrays0 = {k: jnp.asarray(v[0]) for k, v in arrays.items()}
    src, dst = jnp.asarray(art.src[0]), jnp.asarray(art.dst[0])
    h = jnp.asarray(np.random.default_rng(4).normal(
        size=(n_ext, 4)).astype(np.float32))
    w = jnp.asarray(np.random.default_rng(5).normal(
        size=(art.pad_inner, 4)).astype(np.float32))

    g_ell = jax.grad(lambda h: jnp.sum(spmm(arrays0, h) * w))(h)
    g_seg = jax.grad(lambda h: jnp.sum(agg_sum(h, src, dst, art.pad_inner) * w))(h)
    np.testing.assert_allclose(np.asarray(g_ell), np.asarray(g_seg),
                               rtol=1e-5, atol=1e-5)


def test_ell_multi_part_layouts_cover_halo_rows():
    g = synthetic_graph(n_nodes=90, avg_degree=6, n_feat=4, seed=6)
    art = build_artifacts(g, partition_graph(g, 4, method="random", seed=1))
    n_ext = art.pad_inner + art.n_parts * art.pad_boundary
    fwd_spec, bwd_spec, arrays = build_layouts(art.src, art.dst,
                                               art.pad_inner, n_ext)
    spmm = make_ell_spmm(fwd_spec, bwd_spec,
                         len(fwd_spec.widths), len(bwd_spec.widths))
    rng = np.random.default_rng(7)
    for p in range(art.n_parts):
        arrays_p = {k: jnp.asarray(v[p]) for k, v in arrays.items()}
        h = jnp.asarray(rng.normal(size=(n_ext, 4)).astype(np.float32))
        out_ell = spmm(arrays_p, h)
        out_seg = agg_sum(h, jnp.asarray(art.src[p]), jnp.asarray(art.dst[p]),
                          art.pad_inner)
        np.testing.assert_allclose(np.asarray(out_ell), np.asarray(out_seg),
                                   rtol=1e-5, atol=1e-5)
        # backward covers extended (halo) rows too
        ge = jax.grad(lambda h: jnp.sum(spmm(arrays_p, h) ** 2))(h)
        gs = jax.grad(lambda h: jnp.sum(agg_sum(
            h, jnp.asarray(art.src[p]), jnp.asarray(art.dst[p]),
            art.pad_inner) ** 2))(h)
        np.testing.assert_allclose(np.asarray(ge), np.asarray(gs),
                                   rtol=1e-5, atol=1e-5)


def test_split_rows_hub_node_matches_segment():
    """A hub with degree >> ELL_SPLIT_CAP exercises the split-row combine."""
    rng = np.random.default_rng(9)
    n, hub_deg = 400, 1000
    src = np.concatenate([rng.integers(0, n, 800),
                          rng.integers(0, n, hub_deg)]).astype(np.int64)
    dst = np.concatenate([rng.integers(1, n, 800),
                          np.zeros(hub_deg, np.int64)]).astype(np.int64)
    src_a, dst_a = src[None], dst[None]
    fs, bs, arrays = build_layouts(src_a, dst_a, n, n)
    assert fs.n_split > 0 and fs.n_chunks >= hub_deg // 128
    spmm = make_ell_spmm(fs, bs, len(fs.widths), len(bs.widths))
    a0 = {k: jnp.asarray(v[0]) for k, v in arrays.items()}
    h = jnp.asarray(rng.normal(size=(n, 6)).astype(np.float32))
    out = spmm(a0, h)
    expect = agg_sum(h, jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32), n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=1e-5, atol=1e-4)
    # gradient through the split path
    ge = jax.grad(lambda h: jnp.sum(spmm(a0, h) ** 2))(h)
    gs = jax.grad(lambda h: jnp.sum(agg_sum(
        h, jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32), n) ** 2))(h)
    np.testing.assert_allclose(np.asarray(ge), np.asarray(gs), rtol=1e-5, atol=1e-4)


def test_build_ell_numpy_basics():
    src = np.array([0, 1, 2, 3, 4, 5, 0])
    dst = np.array([0, 0, 0, 1, 1, 2, 3])
    widths, rows, idx, perm, _, _, _ = build_ell_numpy(src, dst, n_rows=5, n_src=6)
    # row 4 has degree 0 -> routed to the trailing zero row
    total = sum(rows)
    assert perm[4] == total
    h = np.eye(6, dtype=np.float32)
    # manual check via dense
    a = np.zeros((5, 6))
    np.add.at(a, (dst, src), 1.0)
    from bnsgcn_tpu.ops.ell import EllSpec, _ell_apply
    import jax.numpy as jnp
    spec = EllSpec(widths=widths, rows=rows, n_rows=5, n_src=6)
    out = _ell_apply(spec, [jnp.asarray(i) for i in idx], jnp.asarray(perm),
                     jnp.asarray(h))
    np.testing.assert_allclose(np.asarray(out), a @ h, atol=1e-6)


import pytest


@pytest.mark.parametrize("qmode", ["fp8", "int8"])
def test_quantized_gather_close_to_native(qmode):
    """gather_dtype='fp8'/'int8' ELL SpMM is within quantization tolerance
    of native, forward and backward, and is not a silent no-op. int8 is the
    v5e-native 1-byte wire (fp8 decode is emulated and measured slower than
    bf16 on hardware); its bucket sums run exactly in int32."""
    import jax
    import jax.numpy as jnp
    from bnsgcn_tpu.data.artifacts import build_artifacts
    from bnsgcn_tpu.data.graph import synthetic_graph
    from bnsgcn_tpu.data.partitioner import partition_graph
    from bnsgcn_tpu.ops.ell import build_layouts, make_ell_spmm

    g = synthetic_graph(n_nodes=200, avg_degree=8, n_feat=4, seed=71,
                        power_law=True)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=1))
    f_spec, b_spec, arrays = build_layouts(art.src, art.dst, art.pad_inner,
                                           art.n_ext)
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(size=(art.n_ext, 16)), jnp.float32)
    cot = jnp.asarray(rng.normal(size=(art.pad_inner, 16)), jnp.float32)
    a0 = {k: jnp.asarray(v[0]) for k, v in arrays.items()}
    outs, grads = {}, {}
    for mode in ("native", qmode):
        spmm = make_ell_spmm(f_spec, b_spec, len(f_spec.widths),
                             len(b_spec.widths), gather_dtype=mode)
        outs[mode] = np.asarray(spmm(a0, h))
        grads[mode] = np.asarray(jax.grad(
            lambda hh: jnp.sum(spmm(a0, hh) * cot))(h))
    scale = np.abs(outs["native"]).max() + 1e-9
    assert np.abs(outs[qmode] - outs["native"]).max() / scale < 0.05
    assert not np.allclose(outs[qmode], outs["native"])   # really quantized
    gscale = np.abs(grads["native"]).max() + 1e-9
    assert np.abs(grads[qmode] - grads["native"]).max() / gscale < 0.05


def test_bucket_sum_int8_unroll_exact():
    """int8 rows unroll in int32 chains == the reduce path's int32 sums,
    bit-exact (both are exact integer sums of |q|<=127 over <=128 rows)."""
    import jax.numpy as jnp
    from bnsgcn_tpu.ops.ell import _bucket_sum
    rng = np.random.default_rng(6)
    for w in (2, 16, 32, 128):
        hp = jnp.asarray(rng.integers(-127, 128, size=(400, 16)), jnp.int8)
        idx = jnp.asarray(rng.integers(0, 400, size=(53, w)).astype(np.int32))
        a = np.asarray(_bucket_sum(hp, idx, w, accum="unroll"))
        b = np.asarray(_bucket_sum(hp, idx, w, accum="reduce"))
        assert a.dtype == np.int32 and b.dtype == np.int32
        np.testing.assert_array_equal(a, b)


def test_bucket_sum_fp8_unroll_raises():
    import jax.numpy as jnp
    import pytest as _pytest
    from bnsgcn_tpu.ops.ell import _bucket_sum
    hp = jnp.zeros((8, 4), jnp.float8_e4m3fn)
    idx = jnp.zeros((3, 4), jnp.int32)
    with _pytest.raises(ValueError):
        _bucket_sum(hp, idx, 4, accum="unroll")


def test_bucket_sum_unroll_matches_reduce():
    """The TPU-default unrolled f32-chain accumulation equals the
    materialize-then-reduce path (f32 chains vs bf16 tree: compare in the
    reduce path's own precision envelope)."""
    import jax.numpy as jnp
    from bnsgcn_tpu.ops.ell import _bucket_sum
    rng = np.random.default_rng(5)
    # 16 = largest single unrolled chain, 32 = smallest 2-block scan
    for w in (2, 4, 8, 16, 32, 128):
        hp = jnp.asarray(rng.normal(size=(500, 16)), jnp.float32)
        idx = jnp.asarray(rng.integers(0, 500, size=(37, w)).astype(np.int32))
        a = np.asarray(_bucket_sum(hp, idx, w, accum="unroll"))
        b = np.asarray(_bucket_sum(hp, idx, w, accum="reduce"))
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)
