"""Timer/metrics utilities (reference helper/timer parity)."""

import time

import numpy as np
import pytest

from bnsgcn_tpu.utils.metrics import calc_acc, micro_f1, standard_scale
from bnsgcn_tpu.utils.timers import CommTimer, EpochTimer, estimate_static_hbm


def test_comm_timer_spans_sum_and_clear():
    t = CommTimer()
    with t.timer("forward_0"):
        time.sleep(0.01)
    with t.timer("backward_0"):
        time.sleep(0.01)
    assert t.tot_time() >= 0.02
    with pytest.raises(RuntimeError):
        with t.timer("x"):
            with t.timer("x"):     # non-reentrant (comm_timer.py:14-15)
                pass
    t.clear()
    assert t.tot_time() == 0.0


def test_epoch_timer_warmup_exclusion():
    t = EpochTimer(warmup=5)
    for e in range(10):
        t.record(e, 1.0 if e >= 5 else 100.0, 0.5, 0.1)
    mt, mc, mr = t.means()
    assert mt == 1.0 and mc == 0.5 and abs(mr - 0.1) < 1e-12


def test_micro_f1_and_acc():
    labels = np.array([[1, 0], [0, 1], [1, 1]])
    preds = np.array([[1, 0], [0, 0], [1, 1]])
    assert abs(micro_f1(labels, preds) - 2 * 3 / (2 * 3 + 0 + 1)) < 1e-9
    logits = np.array([[0.9, 0.1], [0.2, 0.8]])
    assert calc_acc(logits, np.array([0, 1])) == 1.0


def test_standard_scale_train_fit():
    rng = np.random.default_rng(0)
    x = rng.normal(loc=5.0, scale=3.0, size=(100, 4)).astype(np.float32)
    mask = np.zeros(100, dtype=bool)
    mask[:60] = True
    y = standard_scale(x, mask)
    np.testing.assert_allclose(y[mask].mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(y[mask].std(0), 1.0, atol=1e-4)


def test_estimate_static_hbm():
    blk = {"a": np.zeros((4, 1000, 10), np.float32)}
    rep = {"w": np.zeros((1000, 10), np.float32)}
    mb = estimate_static_hbm([blk], [rep], n_parts=4)
    expect = (4 * 1000 * 10 * 4 / 4 + 1000 * 10 * 4) / 2**20
    assert abs(mb - expect) < 1e-9
