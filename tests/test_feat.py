"""Feat/tensor-axis exactness (parallel/feat.py + the 3-D mesh).

The acceptance matrix for ('replicas', 'parts', 'feat'):

  (a) --feat 1 is BIT-identical (fwd + bwd) to the historical 2-D/1-D path
      across the full halo-strategy x wire-codec matrix (same pin
      discipline as PR 3's replicas=1);
  (b) --feat 2 forward/grads numerically match --feat 1 within
      psum-ordering tolerance at rate 1.0 and 0.5, including a GAT case
      (heads sharded, ELL attention, dropout on — the masks are drawn at
      full width and sliced, so they are the feat=1 masks exactly);
  (c) checkpoints are feat-invariant: params saved from a feat=2 run are
      unsharded on disk and restore bitwise into a feat=1 template;
  (d) replicas=2 x feat=2 composes on the 8-device CPU mesh: the fused
      psum's gradient equals the mean of the two folded-seed 1-D runs;

plus the partition-rule machinery, the mesh-budget config error, and the
optimizer-state placement satellites.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bnsgcn_tpu import checkpoint as ckpt
from bnsgcn_tpu.config import Config, ConfigError
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.parallel import feat as feat_mod
from bnsgcn_tpu.parallel.mesh import make_parts_mesh
from bnsgcn_tpu.parallel.replicas import (dedup_replica0, make_mesh,
                                          mesh_desc, n_replicas,
                                          replica_axis, stacked_spec)
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                init_training, make_tx, place_blocks,
                                place_replicated)


def _np_tree(t):
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), t)


def _setup(g, n_parts, cfg, spec, mesh, art, params_np, state):
    """Placed step fns + data for one mesh shape; params enter feat-sharded
    when the mesh carries the axis (exactly run.py's placement)."""
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, spec.model)
    blk_np.update(fns.extra_blk)
    blk = place_blocks(blk_np, mesh)
    tables = place_replicated(tables, mesh)
    tables_full = place_replicated(tables_full, mesh)
    if fns.n_feat > 1:
        p = feat_mod.place_params(params_np, mesh, spec)
    else:
        p = place_replicated(params_np, mesh)
    s = place_replicated(state, mesh)
    if spec.use_pp:
        out = fns.precompute(blk, tables_full)
        if spec.model == "gat":
            blk["feat0_ext"] = out
        else:
            blk["feat"] = out
    return fns, blk, tables, p, s


# ----------------------------------------------------------------------------
# mesh construction + dedup
# ----------------------------------------------------------------------------

def test_make_mesh_feat1_is_the_historical_mesh():
    """T=1 must not construct a feat axis at all: same Mesh objects as the
    2-D/1-D constructors, so every compiled program is shared verbatim."""
    m = make_mesh(4, 1, 1)
    m0 = make_parts_mesh(4)
    assert m.axis_names == m0.axis_names == ("parts",)
    assert list(m.devices.flat) == list(m0.devices.flat)
    assert feat_mod.n_feat(m) == 1 and feat_mod.feat_axis(m) is None
    m2 = make_mesh(4, 2, 1)
    assert m2.axis_names == ("replicas", "parts")


def test_make_mesh_3d_layout():
    m = make_mesh(2, 2, 2)
    assert m.axis_names == ("replicas", "parts", "feat")  # feat INNERMOST
    assert m.devices.shape == (2, 2, 2)
    assert feat_mod.n_feat(m) == 2 and feat_mod.feat_axis(m) == "feat"
    assert n_replicas(m) == 2 and replica_axis(m) == "replicas"
    assert mesh_desc(m) == "2x2x2 replicas x parts x feat"
    devs = jax.devices()
    # feat innermost: consecutive device ids share a (replica, part) cell
    assert list(m.devices[0, 0]) == devs[:2]
    assert list(m.devices[0, 1]) == devs[2:4]
    assert list(m.devices[1, 0]) == devs[4:6]
    # replica-free 2-D ('parts','feat') shape
    mf = make_mesh(4, 1, 2)
    assert mf.axis_names == ("parts", "feat")
    assert mesh_desc(mf) == "4x2 parts x feat"
    assert stacked_spec(mf) == P(("parts", "feat"))
    with pytest.raises(ValueError, match="need >= 16 devices"):
        make_mesh(4, 2, 2)


def test_dedup_replica0_strides_past_feat_copies():
    mf = make_mesh(2, 1, 2)
    out = jnp.arange(4 * 3).reshape(4, 3)       # rows: p0f0 p0f1 p1f0 p1f1
    np.testing.assert_array_equal(dedup_replica0(out, mf, 2),
                                  np.asarray(out)[[0, 2]])
    m3 = make_mesh(2, 2, 2)
    out8 = jnp.arange(8 * 3).reshape(8, 3)
    np.testing.assert_array_equal(dedup_replica0(out8, m3, 2),
                                  np.asarray(out8)[[0, 2]])


# ----------------------------------------------------------------------------
# partition rules (fmengine match_partition_rules pattern)
# ----------------------------------------------------------------------------

def test_partition_rules_shard_weights_replicate_biases():
    spec = ModelSpec("graphsage", (6, 8, 3), norm="layer", use_pp=True,
                     train_size=10)
    params, _ = init_params(jax.random.key(0), spec)
    specs = feat_mod.param_specs_for(spec, 2, params)
    # pp layer 0: single [2*6, 8] w row-sharded; layer 1 is a SAGE graph
    # layer — both its linears row-shard, both biases replicate
    assert specs["layer_0"]["w"] == P("feat", None)
    assert specs["layer_0"]["b"] == P()
    assert specs["layer_1"]["linear1"]["w"] == P("feat", None)
    assert specs["layer_1"]["linear2"]["w"] == P("feat", None)
    assert specs["norm_0"]["scale"] == P()

    spec_np = ModelSpec("graphsage", (6, 8, 3), norm="layer", use_pp=False,
                        train_size=10)
    params_np, _ = init_params(jax.random.key(0), spec_np)
    specs_np = feat_mod.param_specs_for(spec_np, 2, params_np)
    assert specs_np["layer_0"]["linear1"]["w"] == P("feat", None)
    assert specs_np["layer_0"]["linear2"]["w"] == P("feat", None)
    assert specs_np["layer_0"]["linear1"]["b"] == P()

    gat = ModelSpec("gat", (6, 8, 3), norm="layer", use_pp=True, heads=2,
                    train_size=10)
    params_g, _ = init_params(jax.random.key(0), gat)
    specs_g = feat_mod.param_specs_for(gat, 2, params_g)
    assert specs_g["layer_0"]["w"] == P(None, "feat")      # heads sharded
    assert specs_g["layer_0"]["attn_l"] == P("feat", None)
    assert specs_g["layer_0"]["bias"] == P("feat")         # per-head bias

    # indivisible widths keep their layer replicated (mixed stacks are fine)
    spec_odd = ModelSpec("gcn", (7, 8, 3), norm="layer", train_size=10)
    assert feat_mod.shardable_layers(spec_odd, 2) == (False, True)
    params_o, _ = init_params(jax.random.key(0), spec_odd)
    specs_o = feat_mod.param_specs_for(spec_odd, 2, params_o)
    assert specs_o["layer_0"]["w"] == P()
    assert specs_o["layer_1"]["w"] == P("feat", None)


def test_place_state_like_shards_adam_moments():
    """Adam mu/nu adopt their weight's sharding (matched by path suffix +
    shape); counts and empty states replicate."""
    spec = ModelSpec("graphsage", (6, 8, 3), norm="layer", use_pp=True,
                     train_size=10)
    mesh = make_mesh(2, 1, 2)
    cfg = Config(lr=0.01, weight_decay=1e-4)
    params, state, opt = init_training(cfg, spec, mesh)
    w = params["layer_0"]["w"]
    assert w.sharding.spec == P("feat", None)
    assert not w.sharding.is_fully_replicated
    shardings = {feat_mod.param_path(p): leaf.sharding for p, leaf in
                 jax.tree_util.tree_flatten_with_path(opt)[0]}
    mu_keys = [k for k in shardings if k.endswith("layer_0/w")]
    assert mu_keys, shardings.keys()
    for k in mu_keys:
        assert shardings[k].spec == P("feat", None), k
    cnt = [sh for k, sh in shardings.items() if k.endswith("count")]
    assert all(sh.is_fully_replicated for sh in cnt)
    # a step runs end-to-end on the sharded state (shapes/placements agree)
    tx = make_tx(cfg)
    grads = jax.tree.map(jnp.ones_like, params)
    updates, opt2 = jax.jit(tx.update)(grads, opt, params)
    assert jax.tree_util.tree_structure(opt2) == \
        jax.tree_util.tree_structure(opt)


# ----------------------------------------------------------------------------
# (a) --feat 1 bit-identity across strategy x wire
# ----------------------------------------------------------------------------

@pytest.fixture(scope="module")
def g_art2():
    """One shared (graph, 2-part artifacts) build for the bitwise matrix —
    12 parametrizations re-partitioning identically would burn tier-1
    budget for nothing (the bitwise property is partition-independent)."""
    g = synthetic_graph(n_nodes=80, avg_degree=5, n_feat=5, n_class=3, seed=32)
    pid = partition_graph(g, 2, method="random", seed=3)
    return g, build_artifacts(g, pid)


@pytest.mark.parametrize("strategy", ["padded", "shift", "ragged"])
@pytest.mark.parametrize("wire", ["native", "bf16", "fp8", "int8"])
def test_feat1_bit_identical_to_2d_path(strategy, wire, g_art2):
    """fwd+bwd (loss_and_grad) through cfg.feat=1 + make_mesh equals the
    pre-feat construction BITWISE for every halo strategy x wire codec."""
    g, art = g_art2
    cfg = Config(model="graphsage", dropout=0.5, use_pp=True, norm="layer",
                 n_train=g.n_train, lr=0.01, sampling_rate=0.5,
                 halo_exchange=strategy, halo_wire=wire, feat=1)
    spec = ModelSpec("graphsage", (5, 8, 3), norm="layer", dropout=0.5,
                     use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(9), spec)
    params_np = _np_tree(params)
    skey, dkey = jax.random.key(0), jax.random.key(1)
    ep = jnp.uint32(1)
    outs = {}
    for tag, mesh in (("new", make_mesh(2, 1, cfg.feat)),
                      ("old", make_parts_mesh(2))):
        fns, blk, tb, p, s = _setup(g, 2, cfg, spec, mesh, art, params_np,
                                    state)
        assert fns.n_feat == 1
        loss, grads = fns.loss_and_grad(p, s, ep, blk, tb, skey, dkey)
        outs[tag] = (np.asarray(loss), _np_tree(grads))

    assert np.array_equal(outs["new"][0], outs["old"][0])   # bitwise
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 outs["new"][1], outs["old"][1])


@pytest.fixture(scope="module")
def g_art6():
    """Shared (graph, 2-part artifacts) at feature width 6 for the feat=2
    exactness / checkpoint / composition tests (same budget argument as
    g_art2)."""
    g = synthetic_graph(n_nodes=80, avg_degree=5, n_feat=6, n_class=3, seed=32)
    pid = partition_graph(g, 2, method="random", seed=3)
    return g, build_artifacts(g, pid)


# ----------------------------------------------------------------------------
# (b) --feat 2 numerically matches --feat 1 (psum-ordering tolerance)
# ----------------------------------------------------------------------------

@pytest.mark.parametrize("model,rate", [("graphsage", 1.0),
                                        ("graphsage", 0.5),
                                        ("gcn", 0.5),
                                        # GAT: heads sharded, ELL attention,
                                        # head-sliced dropout masks
                                        ("gat", 0.5)])
def test_feat2_matches_feat1(model, rate, g_art6):
    """2 parts x 2 feat shards: the per-layer psum of weight-shard partials
    reproduces the feat=1 forward/gradients — same estimator, same BNS
    sample (keys never fold the feat index), same dropout masks; only the
    float summation order differs."""
    g, art = g_art6
    use_pp = model != "gcn"             # gcn non-pp: layer-0 exchange shards
    cfg = Config(model=model, dropout=0.5, use_pp=use_pp, norm="layer",
                 n_train=g.n_train, lr=0.01, sampling_rate=rate,
                 heads=2 if model == "gat" else 1)
    spec = ModelSpec(model, (6, 8, 3), norm="layer", dropout=0.5,
                     use_pp=use_pp, train_size=g.n_train,
                     heads=2 if model == "gat" else 1)
    assert all(feat_mod.shardable_layers(spec, 2))
    params, state = init_params(jax.random.key(9), spec)
    params_np = _np_tree(params)
    skey, dkey = jax.random.key(0), jax.random.key(1)
    ep = jnp.uint32(0)

    mesh2 = make_mesh(2, 1, 2)
    fns2, blk2, tb2, p2, s2 = _setup(g, 2, cfg.replace(feat=2), spec, mesh2,
                                     art, params_np, state)
    assert fns2.n_feat == 2
    l2, g2 = fns2.loss_and_grad(p2, s2, ep, blk2, tb2, skey, dkey)
    l2, g2 = float(l2), _np_tree(g2)
    # grads of sharded leaves device_get back as FULL arrays (unsharded
    # assembly — the same property that keeps checkpoints feat-invariant)
    jax.tree.map(lambda a, b: None if a.shape == b.shape else
                 pytest.fail(f"{a.shape} != {b.shape}"), g2, params_np)

    mesh1 = make_parts_mesh(2)
    fns1, blk1, tb1, p1, s1 = _setup(g, 2, cfg, spec, mesh1, art, params_np,
                                     state)
    l1, g1 = fns1.loss_and_grad(p1, s1, ep, blk1, tb1, skey, dkey)
    l1, g1 = float(l1), _np_tree(g1)

    np.testing.assert_allclose(l2, l1, rtol=1e-5, atol=1e-7)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=1e-4, atol=1e-6), g2, g1)

    # training-mode forward logits dedup to the [P, pad_inner, C] shape and
    # match too (the eval/metrics consumers see identical reports)
    f2 = np.asarray(fns2.forward(p2, s2, ep, blk2, tb2, skey, dkey))
    f1 = np.asarray(fns1.forward(p1, s1, ep, blk1, tb1, skey, dkey))
    assert f2.shape == f1.shape
    np.testing.assert_allclose(f2, f1, rtol=1e-4, atol=1e-5)


# ----------------------------------------------------------------------------
# (c) checkpoint feat-invariance
# ----------------------------------------------------------------------------

def test_checkpoint_feat_invariant(tmp_path, g_art6):
    """Train at feat=2, save, resume at feat=1: the checkpoint carries FULL
    (unsharded) params — restore is bitwise, and the restored tree places
    cleanly back onto either mesh shape."""
    g, art = g_art6
    cfg = Config(model="graphsage", dropout=0.2, use_pp=True, norm="layer",
                 n_train=g.n_train, lr=0.01, sampling_rate=1.0)
    spec = ModelSpec("graphsage", (6, 8, 3), norm="layer", dropout=0.2,
                     use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(9), spec)
    params_np = _np_tree(params)
    skey, dkey = jax.random.key(0), jax.random.key(1)

    mesh2 = make_mesh(2, 1, 2)
    fns2, blk2, tb2, p2, s2 = _setup(g, 2, cfg.replace(feat=2), spec, mesh2,
                                     art, params_np, state)
    _, _, o2 = init_training(cfg.replace(feat=2), spec, mesh2)
    for e in range(2):
        p2, s2, o2, _ = fns2.train_step(p2, s2, o2, jnp.uint32(e), blk2, tb2,
                                        skey, dkey)
    path = str(tmp_path / "feat2.ckpt")
    ckpt.save_checkpoint(path, params=p2, opt_state=o2, bn_state=s2,
                         epoch=1, best_acc=0.5, seed=7)
    p2_np, o2_np = _np_tree(p2), _np_tree(o2)
    # the on-disk tree is already full-width (device_get assembled shards)
    for pth, leaf in jax.tree_util.tree_flatten_with_path(p2_np)[0]:
        full = jax.tree_util.tree_flatten_with_path(params_np)[0]
        shapes = {feat_mod.param_path(q): l.shape for q, l in full}
        assert leaf.shape == shapes[feat_mod.param_path(pth)]

    payload = ckpt.load_checkpoint(path)
    mesh1 = make_parts_mesh(2)
    p1_t, _, _ = init_training(cfg, spec, mesh1)
    rp, ro, rs = ckpt.restore_into(payload, _np_tree(p1_t), o2_np,
                                   _np_tree(s2))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
                 p2_np, rp)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
                 o2_np, ro)
    # and back onto a feat mesh: sharded placement reassembles bitwise
    back = feat_mod.place_params(rp, mesh2, spec)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
                 p2_np, _np_tree(back))


# ----------------------------------------------------------------------------
# (d) replicas x parts x feat composition on the 8-device CPU mesh
# ----------------------------------------------------------------------------

def test_replicas2_feat2_composition(g_art6):
    """2 x 2 x 2: the fused three-axis psum's gradient equals the mean of
    the two folded-seed 1-D runs — the feat axis changes no estimator, the
    replica axis composes with it exactly as on the 2-D mesh."""
    g, art = g_art6
    cfg = Config(model="graphsage", dropout=0.5, use_pp=True, norm="layer",
                 n_train=g.n_train, lr=0.01, sampling_rate=0.5)
    spec = ModelSpec("graphsage", (6, 8, 3), norm="layer", dropout=0.5,
                     use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(9), spec)
    params_np = _np_tree(params)
    skey, dkey = jax.random.key(0), jax.random.key(1)
    ep = jnp.uint32(0)

    mesh3 = make_mesh(2, 2, 2)
    fns3, blk3, tb3, p3, s3 = _setup(g, 2, cfg.replace(replicas=2, feat=2),
                                     spec, mesh3, art, params_np, state)
    assert fns3.n_feat == 2 and fns3.n_replicas == 2
    l3, g3 = fns3.loss_and_grad(p3, s3, ep, blk3, tb3, skey, dkey)
    l3, g3 = float(l3), _np_tree(g3)

    mesh1 = make_parts_mesh(2)
    fns1, blk1, tb1, p1, s1 = _setup(g, 2, cfg, spec, mesh1, art, params_np,
                                     state)
    singles = []
    for r in range(2):
        lr_, gr_ = fns1.loss_and_grad(
            p1, s1, ep, blk1, tb1,
            jax.random.fold_in(skey, r), jax.random.fold_in(dkey, r))
        singles.append((float(lr_), _np_tree(gr_)))
    assert abs(singles[0][0] - singles[1][0]) > 1e-9   # draws truly differ

    np.testing.assert_allclose(l3, (singles[0][0] + singles[1][0]) / 2,
                               rtol=1e-5, atol=1e-7)
    gm = jax.tree.map(lambda a, b: (a + b) / 2, singles[0][1], singles[1][1])
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=1e-4, atol=1e-6), g3, gm)


@pytest.mark.quickgate
def test_run_training_feat2_e2e(tmp_path, capsys):
    """Full run_training on the ('parts','feat') mesh: partitioning,
    precompute, feat-sharded train loop, mesh eval (feat-deduped),
    checkpointing, and the 3-D header with the H/T wire-byte note."""
    from bnsgcn_tpu.run import run_training
    cfg = Config(dataset="sbm", n_partitions=2, feat=2,
                 model="graphsage", n_layers=2, n_hidden=16, n_epochs=12,
                 log_every=5, sampling_rate=0.5, use_pp=True,
                 eval_device="mesh",
                 part_path=str(tmp_path / "parts"),
                 ckpt_path=str(tmp_path / "ckpt"),
                 results_path=str(tmp_path / "res"))
    res = run_training(cfg, verbose=True)
    out = capsys.readouterr().out
    assert "parts x feat" in out                 # 3-D mesh shape reported
    assert "+feat2" in out                       # halo label
    assert "on the parts wire" in out            # per-axis H/T byte note
    assert np.isfinite(res.final_loss)
    assert res.losses[-1] < res.losses[0]
    assert res.best_val_acc > 0.5, res.best_val_acc


# ----------------------------------------------------------------------------
# config validation: one named exit-2 error for the device budget
# ----------------------------------------------------------------------------

def test_mesh_budget_config_error():
    from bnsgcn_tpu.run import check_mesh_budget
    # fits: 8 CPU devices
    check_mesh_budget(Config(n_partitions=2, replicas=2, feat=2))
    with pytest.raises(ConfigError, match=r"shrink --feat to <= 1"):
        check_mesh_budget(Config(n_partitions=4, replicas=2, feat=2))
    with pytest.raises(ConfigError, match=r"shrink --replicas to <= 2"):
        check_mesh_budget(Config(n_partitions=4, replicas=4, feat=1))
    with pytest.raises(ConfigError, match=r"--n-partitions to <= 8"):
        check_mesh_budget(Config(n_partitions=16, replicas=1, feat=1))
    # run_training surfaces it before any mesh/axis constructor can throw
    # its own partial error
    from bnsgcn_tpu.run import run_training
    with pytest.raises(ConfigError, match="mesh does not fit"):
        run_training(Config(dataset="sbm", n_partitions=4, replicas=2,
                            feat=2, skip_partition=True), verbose=False)
