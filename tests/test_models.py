"""Model-semantics tests: eval-path forward vs hand-rolled dense numpy math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bnsgcn_tpu.data.graph import synthetic_graph
from bnsgcn_tpu.evaluate import build_eval_env, full_graph_logits
from bnsgcn_tpu.models.gnn import ModelSpec, apply_model, init_params


def _dense_gcn(g, params, spec):
    """Eval-path GCN in numpy: h/sqrt(out_deg) -> A @ . -> /sqrt(in_deg) -> W."""
    a = g.dense_adj()
    in_n = np.sqrt(g.in_degrees())[:, None]
    out_n = np.sqrt(g.out_degrees())[:, None]
    h = np.asarray(g.feat, np.float64)
    for i in range(spec.n_layers):
        p = jax.tree.map(lambda x: np.asarray(x, np.float64), params[f"layer_{i}"])
        if i < spec.n_graph_layers:
            h = (a @ (h / out_n)) / in_n @ p["w"] + p["b"]
        else:
            h = h @ p["w"] + p["b"]
        if i < spec.n_layers - 1:
            if spec.norm == "layer":
                q = params[f"norm_{i}"]
                mu = h.mean(-1, keepdims=True)
                var = ((h - mu) ** 2).mean(-1, keepdims=True)
                h = (h - mu) / np.sqrt(var + 1e-5) * np.asarray(q["scale"]) + np.asarray(q["bias"])
            h = np.maximum(h, 0)
    return h


def _dense_sage(g, params, spec):
    a = g.dense_adj()
    deg = g.in_degrees().astype(np.float64)[:, None]
    h = np.asarray(g.feat, np.float64)
    for i in range(spec.n_layers):
        pr = params[f"layer_{i}"]
        if i < spec.n_graph_layers:
            ah = (a @ h) / deg
            if spec.use_pp and i == 0:
                p = jax.tree.map(np.asarray, pr)
                h = np.concatenate([h, ah], 1) @ p["w"] + p["b"]
            else:
                p1 = jax.tree.map(np.asarray, pr["linear1"])
                p2 = jax.tree.map(np.asarray, pr["linear2"])
                h = h @ p1["w"] + p1["b"] + ah @ p2["w"] + p2["b"]
        else:
            p = jax.tree.map(np.asarray, pr)
            h = h @ p["w"] + p["b"]
        if i < spec.n_layers - 1:
            if spec.norm == "layer":
                q = params[f"norm_{i}"]
                mu = h.mean(-1, keepdims=True)
                var = ((h - mu) ** 2).mean(-1, keepdims=True)
                h = (h - mu) / np.sqrt(var + 1e-5) * np.asarray(q["scale"]) + np.asarray(q["bias"])
            h = np.maximum(h, 0)
    return h


@pytest.mark.parametrize("norm", ["layer", None])
def test_gcn_eval_matches_dense(norm):
    g = synthetic_graph(n_nodes=40, avg_degree=5, n_feat=6, n_class=3, seed=7)
    spec = ModelSpec("gcn", (6, 8, 3), norm=norm, dropout=0.0)
    params, state = init_params(jax.random.key(0), spec)
    logits = full_graph_logits(params, state, spec, g)
    expect = _dense_gcn(g, params, spec)
    np.testing.assert_allclose(logits, expect, rtol=1e-4, atol=1e-4)


@pytest.mark.quickgate
@pytest.mark.parametrize("use_pp", [False, True])
def test_sage_eval_matches_dense(use_pp):
    g = synthetic_graph(n_nodes=35, avg_degree=4, n_feat=5, n_class=4, seed=8)
    spec = ModelSpec("graphsage", (5, 8, 4), norm="layer", dropout=0.0, use_pp=use_pp)
    params, state = init_params(jax.random.key(1), spec)
    logits = full_graph_logits(params, state, spec, g)
    expect = _dense_sage(g, params, spec)
    np.testing.assert_allclose(logits, expect, rtol=1e-4, atol=1e-4)


def test_sage_n_linear_tail():
    g = synthetic_graph(n_nodes=30, avg_degree=4, n_feat=5, n_class=3, seed=9)
    spec = ModelSpec("graphsage", (5, 8, 8, 3), n_linear=2, norm="layer", dropout=0.0)
    params, state = init_params(jax.random.key(2), spec)
    logits = full_graph_logits(params, state, spec, g)
    expect = _dense_sage(g, params, spec)
    np.testing.assert_allclose(logits, expect, rtol=1e-4, atol=1e-4)
    # tail layers must be plain {'w','b'} linears
    assert set(params["layer_2"].keys()) == {"w", "b"}


def test_gat_eval_shapes_and_softmax():
    g = synthetic_graph(n_nodes=20, avg_degree=4, n_feat=5, n_class=3, seed=10)
    spec = ModelSpec("gat", (5, 8, 3), norm="layer", dropout=0.0, heads=2, use_pp=True)
    params, state = init_params(jax.random.key(3), spec)
    logits = full_graph_logits(params, state, spec, g)
    assert logits.shape == (g.n_nodes, 3)
    assert np.all(np.isfinite(logits))


def test_dropout_off_in_eval_and_deterministic():
    g = synthetic_graph(n_nodes=25, avg_degree=4, n_feat=5, n_class=3, seed=11)
    spec = ModelSpec("graphsage", (5, 8, 3), norm="layer", dropout=0.5)
    params, state = init_params(jax.random.key(4), spec)
    a = full_graph_logits(params, state, spec, g)
    b = full_graph_logits(params, state, spec, g)
    np.testing.assert_array_equal(a, b)
