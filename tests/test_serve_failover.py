"""Self-healing serving fleet (serve_router.py health machinery): the
unit matrix for the pure state — HealthState transitions including the
circuit breaker, the bounded failover DeltaWAL, the --inject serve-fault
grammar, degraded answer payloads, incarnation-token refusal, and
health-filtered fleet snapshots — plus the subprocess e2e: a 2x2 fleet
through servekill@3:p0.r0 mid-load with ZERO failed client answers,
a WAL-queued delta during the outage, and the relaunched backend
rejoining bitwise through WAL replay + warm-up.
The schedule-exploration twin lives in analysis/proto (router-failover /
rejoin-stale-incarnation / wal-replay-vs-live-delta scenarios)."""

import time

import numpy as np
import pytest

from bnsgcn_tpu import resilience
from bnsgcn_tpu import serve_router as sr
from bnsgcn_tpu.config import ConfigError

_silent = lambda *a, **k: None  # noqa: E731


def _policy(suspect_after=1, down_after=2, readmit=1, breaker_flaps=99,
            breaker_window_s=100.0, breaker_hold_s=5.0, spotcheck=1):
    """Env-independent policy: every threshold pinned explicitly so the
    unit matrix is immune to BNSGCN_SERVE_* leaking in from the host."""
    pol = sr.HealthPolicy(0.0)
    pol.probe_timeout_s = 0.2
    pol.suspect_after = suspect_after
    pol.down_after = down_after
    pol.readmit = readmit
    pol.breaker_flaps = breaker_flaps
    pol.breaker_window_s = breaker_window_s
    pol.breaker_hold_s = breaker_hold_s
    pol.spotcheck = spotcheck
    pol.hedge_floor_ms = 0.0
    return pol


# ----------------------------------------------------------------------------
# HealthState: every transition of the up/suspect/down/quarantined machine
# ----------------------------------------------------------------------------

def test_health_up_suspect_down_thresholds():
    hs = sr.HealthState(_policy(suspect_after=2, down_after=3))
    assert hs.on_fail(1.0) is None and hs.state == "up"
    assert hs.on_fail(2.0) == "suspect"
    assert hs.on_fail(3.0) == "down"
    assert hs.down_since == 3.0


def test_health_suspect_recovers_to_up_without_warmup():
    hs = sr.HealthState(_policy(suspect_after=1, down_after=3, readmit=2))
    assert hs.on_fail(1.0) == "suspect"
    assert hs.on_ok(2.0) is None        # streak 1/2
    assert hs.on_ok(3.0) == "up"        # no warm-up gate: never left
    assert hs.state == "up" and hs.oks == 0


def test_health_ok_resets_fail_streak():
    hs = sr.HealthState(_policy(suspect_after=2, down_after=3))
    hs.on_fail(1.0)
    hs.on_ok(2.0)
    assert hs.on_fail(3.0) is None      # streak restarted, still up
    assert hs.state == "up"


def test_health_down_earns_ready_then_admit_reports_outage():
    hs = sr.HealthState(_policy(down_after=2, readmit=2))
    hs.on_fail(1.0)
    assert hs.on_fail(2.0) == "down"
    assert hs.on_ok(3.0) is None
    assert hs.on_ok(4.0) == "ready"     # warm-up gate before up
    assert hs.state == "down"           # ready is a verdict, not a state
    assert hs.admit(10.0) == pytest.approx(8.0)
    assert hs.state == "up" and hs.down_since is None


def test_health_reject_warmup_re_earns_whole_streak():
    hs = sr.HealthState(_policy(down_after=1, readmit=2))
    hs.on_fail(1.0)
    hs.on_ok(2.0)
    assert hs.on_ok(3.0) == "ready"
    hs.reject_warmup()                  # spot-check failed: stay down
    assert hs.state == "down"
    assert hs.on_ok(4.0) is None        # streak starts over
    assert hs.on_ok(5.0) == "ready"


def test_health_breaker_quarantines_flapping_backend():
    hs = sr.HealthState(_policy(down_after=1, readmit=1, breaker_flaps=2,
                                breaker_window_s=100.0, breaker_hold_s=5.0))
    assert hs.on_fail(1.0) == "down"    # flap 1
    assert hs.on_ok(2.0) == "ready"
    hs.admit(2.0)
    assert hs.on_fail(3.0) == "quarantined"   # flap 2 inside the window
    assert hs.hold_until == pytest.approx(8.0)
    assert hs.on_ok(4.0) is None        # breaker holds: successes ignored
    assert hs.state == "quarantined"
    assert hs.on_ok(9.0) == "ready"     # hold expired: resumes as down,
    assert hs.state == "down"           # must re-earn the streak + warm-up


def test_health_breaker_window_forgets_old_flaps():
    hs = sr.HealthState(_policy(down_after=1, readmit=1, breaker_flaps=2,
                                breaker_window_s=10.0))
    assert hs.on_fail(1.0) == "down"
    hs.on_ok(2.0)
    hs.admit(2.0)
    # second flap lands OUTSIDE the window: plain down, no quarantine
    assert hs.on_fail(50.0) == "down"
    assert hs.state == "down"


# ----------------------------------------------------------------------------
# DeltaWAL: bound, commit order, per-replica cursors, retirement
# ----------------------------------------------------------------------------

def test_wal_orders_pending_per_replica_and_retires_full_entries():
    wal = sr.DeltaWAL(cap=8, slots=2)
    s1 = wal.record(0, {"op": "apply_feat", "node": 1}, taken={0})
    s2 = wal.record(0, {"op": "apply_delta", "edges": [[1, 2]]}, taken=set())
    assert s1 is not None and s2 == s1 + 1
    # replica 1 missed both, replica 0 only the second — commit order
    assert [op["op"] for _, op in wal.pending_for(0, 1)] == \
           ["apply_feat", "apply_delta"]
    assert [seq for seq, _ in wal.pending_for(0, 0)] == [s2]
    wal.mark_taken(0, 1, [s1])          # every slot took s1: it retires
    assert wal.depth(0) == 1
    assert wal.pending_for(0, 1) == [(s2, {"op": "apply_delta",
                                           "edges": [[1, 2]]})]
    wal.mark_taken(0, 0, [s2])
    wal.mark_taken(0, 1, [s2])
    assert wal.depth(0) == 0 and wal.snapshot() == {}
    assert wal.queued == 2
    assert wal.replayed == 3            # three per-replica confirmations


def test_wal_skips_fully_taken_and_bounds_per_part():
    wal = sr.DeltaWAL(cap=2, slots=2)
    assert wal.record(0, {"op": "mark"}, taken={0, 1}) is None
    assert wal.depth(0) == 0            # every slot took it: nothing queued
    wal.record(0, {"op": "a"}, taken=set())
    wal.record(0, {"op": "b"}, taken=set())
    with pytest.raises(sr.RouteError, match="WAL full"):
        wal.record(0, {"op": "c"}, taken=set())
    # the bound is per part: another part still has room
    assert wal.record(1, {"op": "c"}, taken=set()) is not None


# ----------------------------------------------------------------------------
# --inject serve-fault grammar (resilience.ServeFaultPlan)
# ----------------------------------------------------------------------------

def test_serve_fault_plan_targets_one_backend():
    plan = resilience.ServeFaultPlan.parse("servekill@3:p0.r1",
                                           part=0, replica=1)
    assert plan.faults == {"servekill": {3}}
    assert not plan.pop("servekill", 2)
    assert plan.pop("servekill", 3)
    assert not plan.pop("servekill", 3)     # fires exactly once
    assert plan.empty()
    # the same term scoped to a DIFFERENT backend parses to nothing
    other = resilience.ServeFaultPlan.parse("servekill@3:p0.r1",
                                            part=1, replica=0)
    assert other.empty()


def test_serve_fault_plan_servedrop_may_stay_fleet_wide():
    plan = resilience.ServeFaultPlan.parse("servedrop@2", part=1, replica=1)
    assert plan.pop("servedrop", 2)


def test_serve_fault_plan_ignores_training_terms():
    plan = resilience.ServeFaultPlan.parse("nan@E5,servedrop@2,sigterm@E3",
                                           part=0, replica=0)
    assert plan.faults == {"servedrop": {2}}


def test_serve_fault_plan_grammar_errors():
    with pytest.raises(ConfigError, match="needs an explicit"):
        resilience.ServeFaultPlan.parse("servekill@3")     # no target
    with pytest.raises(ValueError, match="bad --inject term"):
        resilience.ServeFaultPlan.parse("servehang@x:p0.r0")
    with pytest.raises(ValueError, match="backend target"):
        resilience.ServeFaultPlan.parse("servekill@3:r0.p0")


# ----------------------------------------------------------------------------
# RouterCore units (no sockets: the only registered backend is marked down
# before anything would dial it, so every path below is pure in-memory —
# except stale-ok, which dials port 1 once and times out in ~0.25 s)
# ----------------------------------------------------------------------------

def _down_core(degraded):
    core = sr.RouterCore(np.zeros(4, dtype=np.int32), 1, replicas=1,
                         hops=1, log=_silent, route_timeout_s=0.5,
                         delta_timeout_s=0.5, health=_policy(),
                         degraded=degraded)
    core.register_backend(0, 0, "127.0.0.1", 1, incarnation="inc-A")
    core._note_fail(0, 0, "unit: process died")
    core._note_fail(0, 0, "unit: process died")
    assert core.health_snapshot()["p0.r0"] == "down"
    return core


def test_degraded_partial_answers_tagged_unavailable_rows():
    core = _down_core("partial")
    row = core.predict(2)
    assert row["ok"] is True and row["status"] == "unavailable"
    assert row["node"] == 2 and row["part"] == 0 and "err" in row
    rows = core.predict_many([0, 3])
    assert [r["node"] for r in rows] == [0, 3]
    assert all(r["status"] == "unavailable" for r in rows)
    assert core.stats["requests_degraded"] == 3
    assert core.stats["requests_failed"] == 0
    core.close()


def test_degraded_off_raises_and_counts_failed():
    core = _down_core("off")
    with pytest.raises(sr.RouteError, match="no live backend"):
        core.predict(0)
    assert core.stats["requests_failed"] == 1
    core.close()


def test_degraded_stale_ok_falls_back_to_unavailable_when_unreachable():
    # stale-ok first tries a possibly-stale tier-A batch from ANY
    # registered replica; with the only one unreachable it must still
    # degrade the answer, not fail the request
    core = _down_core("stale-ok")
    row = core.predict(1)
    assert row["ok"] is True and row["status"] == "unavailable"
    core.close()


def test_stale_incarnation_token_is_refused():
    core = _down_core("partial")
    # respawn registers a fresh token: inc-A is retired, slot re-admitted
    # (replicas=1: WAL empty + no up peer means trivially-true warm-up)
    resp = core.register_backend(0, 0, "127.0.0.1", 2, incarnation="inc-B")
    assert resp["state"] == "up"
    with pytest.raises(sr.RouteError, match="stale incarnation"):
        core.register_backend(0, 0, "127.0.0.1", 3, incarnation="inc-A")
    # the zombie never displaced the live endpoint... and the CURRENT
    # token may re-register (same process reconnecting is not a zombie)
    assert core.fleet.endpoint(0, 0)["port"] == 2
    core.register_backend(0, 0, "127.0.0.1", 2, incarnation="inc-B")
    core.close()


def test_fleet_snapshot_drops_down_replicas_unless_all_down():
    core = sr.RouterCore(np.zeros(4, dtype=np.int32), 1, replicas=2,
                         hops=1, log=_silent, route_timeout_s=0.5,
                         health=_policy(), degraded="partial")
    core.register_backend(0, 0, "127.0.0.1", 1, incarnation="a")
    core.register_backend(0, 1, "127.0.0.1", 2, incarnation="b")
    core._note_fail(0, 0, "unit")
    core._note_fail(0, 0, "unit")
    entries = core.fleet_snapshot()["0"]
    assert [e["replica"] for e in entries] == [1]   # down replica filtered
    core._note_fail(0, 1, "unit")
    core._note_fail(0, 1, "unit")
    entries = core.fleet_snapshot()["0"]
    # every replica down: the raw list stays so errors name dead backends
    assert sorted(e["replica"] for e in entries) == [0, 1]
    core.close()


def test_write_fanout_skips_down_replica_and_wal_queues():
    core = _down_core("partial")
    out = core.update_feat(0, [1.0, 2.0])           # only replica is down
    assert out == {"ok": True, "dirty_new": 0, "dirty_total": 0}
    # both the feature write and its dirty-mark wave queued for the slot
    assert core.wal.depth(0) == 2
    assert core.wal.pending_for(0, 0)[0][1]["op"] == "apply_feat"
    assert core.stats["wal_queued"] == core.wal.queued == 2
    core.close()


# ----------------------------------------------------------------------------
# subprocess e2e: kill -> failover -> WAL -> rejoin, through the real CLI
# ----------------------------------------------------------------------------

@pytest.mark.quickgate
def test_e2e_servekill_failover_and_bitwise_rejoin(tmp_path, monkeypatch):
    """2 parts x 2 replicas behind a probing router in degraded 'partial'
    mode; p0.r0 dies hard (--inject servekill@3:p0.r0) under client load.
    Zero client answers may fail or degrade (its peer replica covers), a
    delta landing during the outage queues in the failover WAL, and the
    relaunched process (fresh incarnation) rejoins through WAL replay +
    warm-up — after which both p0 replicas answer tier-A bitwise."""
    from test_serve_dist_e2e import (_dump, _free_port, _setup_fleet_dirs,
                                     _spawn)
    from bnsgcn_tpu import serve

    monkeypatch.setenv("BNSGCN_SERVE_DOWN_AFTER", "2")  # subprocesses inherit
    args, g, cfg2, params, state, owner = _setup_fleet_dirs(tmp_path)
    rport = _free_port()
    router = _spawn("serve-router", args,
                    ["--serve-port", str(rport), "--part-replicas", "2",
                     "--serve-degraded", "partial", "--serve-probe-s", "0.2"])
    procs = [("router", router)]

    def backend(part, rep, extra=()):
        b = _spawn("serve-backend", args,
                   ["--serve-part", str(part), "--serve-replica", str(rep),
                    "--serve-router", f"127.0.0.1:{rport}",
                    "--serve-dir", str(tmp_path / f"sdir{part}{rep}"),
                    *extra])
        procs.append((f"backend p{part}.r{rep}", b))
        return b

    victim = backend(0, 0, ["--inject", "servekill@3:p0.r0"])
    for part, rep in ((0, 1), (1, 0), (1, 1)):
        backend(part, rep)

    def req(payload, timeout_s=60.0):
        return serve.request(rport, payload, timeout_s=timeout_s)

    def bad_rows(resp):
        rows = resp["results"] if resp.get("ok") else [resp]
        return [x for x in rows
                if not x.get("ok") or x.get("status", "ok") != "ok"]

    try:
        deadline = time.monotonic() + 300
        while True:
            for name, p in procs:
                if p.poll() is not None:
                    raise AssertionError(f"{name} died rc={p.returncode}:\n"
                                         f"{_dump(procs)}")
            try:
                r = req({"op": "fleet"}, timeout_s=2.0)
                if r.get("ok") and not r.get("missing_parts"):
                    break
            except Exception:
                pass
            assert time.monotonic() < deadline, f"fleet:\n{_dump(procs)}"
            time.sleep(0.5)

        nodes = [int(n) for n in np.flatnonzero(owner == 0)[:5]] + \
                [int(n) for n in np.flatnonzero(owner == 1)[:5]]
        bad = []
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:      # load until the kill lands
            bad += bad_rows(req({"op": "predict_many", "nodes": nodes}))
            h = req({"op": "health"}, timeout_s=5.0)
            if h["health"].get("p0.r0") in ("down", "quarantined"):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(f"p0.r0 never marked down:\n{_dump(procs)}")
        for _ in range(2):                      # failover keeps serving
            bad += bad_rows(req({"op": "predict_many", "nodes": nodes}))
        assert bad == [], f"client saw bad answers through the kill: {bad}"
        assert victim.wait(timeout=60) == 1     # os._exit(1), no drain

        # a delta lands during the outage: the WAL queues it for the slot
        r = req({"op": "add_edges",
                 "edges": [[nodes[0], nodes[5]], [nodes[5], nodes[0]]]},
                timeout_s=120.0)
        assert r.get("ok"), r
        h = req({"op": "health"}, timeout_s=5.0)
        assert sum(h["wal_depth"].values()) > 0

        # relaunch: fresh incarnation, WAL replay, warm-up, back to 'up'
        backend(0, 0)
        deadline = time.monotonic() + 300
        while True:
            h = req({"op": "health"}, timeout_s=5.0)
            if h["health"].get("p0.r0") == "up":
                break
            assert time.monotonic() < deadline, \
                f"rejoin stuck {h['health']}:\n{_dump(procs)}"
            time.sleep(0.5)
        assert sum(h["wal_depth"].values()) == 0    # cursor drained
        stats = req({"op": "stats"}, timeout_s=60.0)
        assert stats["wal_replayed"] > 0
        assert h["availability"]["requests_failed"] == 0

        # rejoined replica is bitwise: flush the dirty frontier, then both
        # p0 replicas must answer identical tier-A scores directly
        assert req({"op": "flush"}, timeout_s=300.0)["ok"]
        p0 = req({"op": "fleet"})["parts"]["0"]
        assert len(p0) == 2
        for v in nodes[:5]:
            answers = [serve.request(e["port"],
                                     {"op": "predict", "node": v,
                                      "tier": "A"}, timeout_s=60.0)
                       for e in p0]
            assert all(a.get("ok") for a in answers), (v, answers)
            assert answers[0]["scores"] == answers[1]["scores"], f"node {v}"

        req({"op": "shutdown"}, timeout_s=30.0)
        assert router.wait(timeout=120) == 0, _dump(procs)
        for name, p in procs[1:]:
            if p is victim:
                continue
            assert p.wait(timeout=120) == 0, f"{name}:\n{_dump(procs)}"
    finally:
        for _, p in procs:
            if p.poll() is None:
                p.kill()
