"""Flagship benchmark — prints JSON lines for the driver; LAST line wins.

A provisional best-so-far JSON line is emitted as each SpMM candidate is
measured, so an outer timeout that kills the process mid-matrix still leaves
a valid result on stdout; consumers must parse the LAST JSON line.

Crash-proofing (round 3): the axon TPU tunnel has two observed failure
modes — backend init raises UNAVAILABLE fast, or jax.devices() HANGS
indefinitely (a killed mid-compile can wedge the tunnel). Neither may ever
again produce an artifact with no parseable JSON (round 2's driver capture
was a stack trace). So `python bench.py` now runs a SUPERVISOR that
  1. immediately prints a carried-forward JSON line (best known measured
     number + "status" field) so even a SIGKILL seconds later leaves data,
  2. probes backend liveness in a subprocess with a hard timeout,
     retrying with backoff inside --probe-budget-s,
  3. re-execs itself as a worker (BNSGCN_BENCH_WORKER=1) under a hard
     timeout, relaunching after mid-run failures while budget remains,
  4. on final failure emits a JSON line with status="tpu-unavailable" and
     the last-known-best value, exit code 0.
Real measurements update bench_cache/best_known.json; the carried-forward
line is labeled by its "status"/"measured_at" fields so a stale number can
never masquerade as a fresh one.

Workload: one rank's share of the reference's headline config (BASELINE.md /
reference scripts/reddit.sh: Reddit — 232,965 nodes, ~114.6M directed edges
(mean degree ~492), 602 features, 41 classes — GraphSAGE 4-layer hidden=256,
use_pp, BNS rate 0.1, P=2, 0.3578 s/epoch/rank on 2x NVIDIA >=11GB GPUs,
README.md:94-95). The real dataset is not downloadable here (zero egress), so
a synthetic power-law graph with the same shape statistics stands in:
scale x 232,965 nodes at the true ~492 mean degree (scale 0.5 = the P=2
per-rank node share, ~57M local edges).

vs_baseline = 0.3578 / measured_epoch_time (>1 == faster per chip than the
reference per GPU). Compute dtype defaults to bf16 — the TPU-native choice.
The v5e gather unit moves 512B rows at ~110 GB/s (the pure-ELL bound); the
hybrid block-dense SpMM routes clustered edge mass through the MXU instead,
and scale-out (BNS partition parallelism over the 'parts' mesh axis)
divides the rest. See BENCH_NOTES.md for the candidate/guard scheme.

Usage: python bench.py [--epochs N] [--scale S] [--avg-degree D]
                       [--dtype bf16|f32] [--json-only]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

BASELINE_EPOCH_S = 0.3578   # reference README.md:94 (rank 0, Reddit P=2 rate=0.1)

# versioned-pickle cache helpers shared with the trainer's --cache-dir
# layout persistence (bnsgcn_tpu/utils/diskcache.py)
from bnsgcn_tpu.utils.diskcache import (atomic_dump as _atomic_dump,
                                        disk_cached as _disk_cached,
                                        try_load as _try_load)

# Seeded fallback if bench_cache/best_known.json is absent (e.g. a container
# restart wipes the gitignored cache — it happened mid-queue at 07:05 on
# 2026-07-31): the best number actually measured on the v5e chip for each
# workload, read from the committed hardware logs. The dcsbm value is the
# round-5 reproduction of the round-4 headline (hw_logs/r5_confirm.log:
# hybrid+pallas 0.5715 s/epoch, independently measured twice ~12 h apart);
# uniform is the round-2/4 ELL anchor band. Seeds carry no measured_epoch,
# so a carried-forward line built from one is labeled status=tpu-unavailable
# without an age — exactly as honest as a lost cache allows.
_SEED_BEST = {
    "dcsbm_0.5_492": {"value": 0.5715, "spmm": "hybrid+pallas",
                      "measured_at": "2026-07-31 round-5 v5e window "
                                     "(hw_logs/r5_confirm.log)"},
    "uniform_0.5_492": {"value": 1.672, "spmm": "ell",
                        "measured_at": "2026-07-29 round-2 v5e window"},
}


# ---------------------------------------------------------------------------
# online-serving metric vocabulary (tools/serve_bench.py emits these; the
# driver captures them into BENCH_*.json exactly like the epoch-time lines).
# Names are load-bearing: a rename silently orphans every recorded BENCH
# file, so serve_bench imports THIS table instead of spelling its own.
# ---------------------------------------------------------------------------

SERVE_METRICS = {
    "serve_p50_ms": "ms",          # per-request latency median, per tier
    "serve_p99_ms": "ms",          # per-request latency 99th pct, per tier
    "serve_qps": "req/s/chip",     # sustained throughput per accelerator chip
}


def emit_serve_metric(name: str, value: float, tier: str | None = None,
                      **extra):
    """One driver-parsed JSON metric line for the serving bench (same
    last-line-wins contract as the epoch-time emitter above)."""
    if name not in SERVE_METRICS:
        raise ValueError(f"unknown serve metric {name!r} "
                         f"(vocabulary: {sorted(SERVE_METRICS)})")
    line = {"metric": name, "value": round(float(value), 4),
            "unit": SERVE_METRICS[name]}
    if tier is not None:
        line["tier"] = tier
    line.update(extra)
    print(json.dumps(line), flush=True)


def _workload_tag(args) -> str:
    tag = f"{args.graph}_{args.scale:g}_{args.avg_degree}"
    # non-flagship models get their own best_known/anchor namespace (a GAT
    # epoch time must never be compared against, or overwrite, a GraphSAGE
    # one); the suffix-free tag keeps existing graphsage entries valid
    if args.model != "graphsage":
        tag += f"_{args.model}"
    return tag


def _metric_name(args) -> str:
    """Driver-parsed metric id. The flagship GraphSAGE workload keeps the
    historical name (BENCH_r0*.json continuity); other models get their
    own. vs_baseline is only emitted for the flagship — the reference
    publishes no in-repo GAT epoch time to normalize against
    (README.md:94-95 is the GraphSAGE run)."""
    if args.model == "graphsage":
        return "reddit_rank_share_epoch_time_per_chip"
    return f"reddit_{args.model}_rank_share_epoch_time_per_chip"


def _best_known_path(args) -> str:
    return os.path.join(args.cache_dir, "best_known.json")


def _load_tag_entry(args):
    """Raw best_known.json entry for this workload (no field filtering) —
    anchor-only entries (measured losses but no epoch time yet) are valid
    here, unlike for _load_best_known's carried-forward line."""
    try:
        with open(_best_known_path(args)) as f:
            return json.load(f).get(_workload_tag(args))
    except Exception:
        return None


def _load_best_known(args):
    """Best measured result for this workload: file first, seed second."""
    ent = _load_tag_entry(args)
    if ent and isinstance(ent.get("value"), (int, float)):
        return ent
    return _SEED_BEST.get(_workload_tag(args))


def _update_best_known(args, mutate):
    """Load best_known.json, apply `mutate(entry)` to this workload's entry
    IN PLACE (never replace the dict — entries carry independent field
    families: best value + anchor losses), atomic rewrite. Shared by
    _record_best/_record_anchor so their write behavior cannot drift."""
    path = _best_known_path(args)
    try:
        try:
            with open(path) as f:
                d = json.load(f)
        except Exception:
            d = {}
        mutate(d.setdefault(_workload_tag(args), {}))
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(d, f, indent=1)
        os.replace(tmp, path)
    except Exception as ex:           # never let bookkeeping kill the bench
        print(f"  best_known.json update failed: {ex}", file=sys.stderr)


def _record_best(args, value: float, spmm: str):
    """Persist a fresh hardware measurement for future carried-forward use
    (only called from the worker after a gated, measured epoch time)."""
    def mutate(ent):
        prev = ent.get("value")
        if prev is None or value < prev:
            # measured_epoch (numeric) is what the supervisor compares for
            # partial-vs-tpu-unavailable: human-readable strings are for
            # humans only (lexicographic compare of free-text timestamps
            # misclassified the seed data — round-3 advisor finding)
            ent.update(value=round(value, 4), spmm=spmm,
                       measured_at=time.strftime("%Y-%m-%d %H:%M:%S"),
                       measured_epoch=time.time())
        else:
            # the measurement is fresh even when it doesn't beat the stored
            # best: stamp it so the supervisor's fallback classifies this
            # run as "partial" (hardware was up and measured), not
            # "tpu-unavailable"
            ent["last_measured_epoch"] = time.time()
    _update_best_known(args, mutate)


def _anchor_cfg(args):
    """The knobs the anchor's losses depend on beyond the workload tag."""
    return [args.epochs, args.dtype, args.hidden, args.layers]


def _record_anchor(args, l0: float, lf: float):
    """Persist the measured ell-anchor step-0/final losses so --skip-anchor
    runs (short tunnel windows) can gate candidates against them without
    re-measuring the anchor. Deterministic per (workload, anchor_cfg):
    the artifacts, init key and epoch keys are all fixed."""
    def mutate(ent):
        ent.update(anchor_l0=round(l0, 6), anchor_lf=round(lf, 6),
                   anchor_cfg=_anchor_cfg(args))
    _update_best_known(args, mutate)


def _vhalo(v):
    """Halo-exchange strategy of a variant tuple. Variants grew a 6th field
    for the ragged exchange; 5-tuples (every pre-existing name) mean
    'padded', so queued lines and best_known entries stay valid."""
    return v[5] if len(v) > 5 else "padded"


def _vovl(v):
    """Overlap mode of a variant tuple (7th field, PR 2's interior/frontier
    split aggregation); shorter tuples mean 'off' — pre-existing names and
    queue lines stay valid."""
    return v[6] if len(v) > 6 else "off"


def _vrep(v):
    """Replica-axis size of a variant tuple (8th field: the 2-D
    ('replicas','parts') mesh of parallel/replicas.py — N independently-
    BNS-sampled graph replicas, fused cross-replica gradient mean); shorter
    tuples mean 1 — pre-existing names and queue lines stay valid."""
    return v[7] if len(v) > 7 else 1


def _vfeat(v):
    """Feat-axis size of a variant tuple (9th field: parallel/feat.py's
    tensor axis — hidden dimensions sharded T-ways, H/T halo payloads, one
    feat psum per layer); shorter tuples mean 1 — pre-existing names and
    queue lines stay valid."""
    return v[8] if len(v) > 8 else 1


def _vhr(v):
    """Halo-refresh period K of a variant tuple (10th field: the staleness-
    bounded cached-halo reuse of parallel/halo.py — epoch 0 pays the full
    exchange, steady-state epochs redraw only chunk epoch%K, ~1/K the wire
    bytes); shorter tuples mean 1 — pre-existing names and queue lines stay
    valid."""
    return v[9] if len(v) > 9 else 1


def _vro(v):
    """Reorder mode of a variant tuple (11th field: the data/reorder
    LPA+FFD artifact permutation, --reorder; 'cluster' bakes the
    tile-coverage-maximizing row order into the artifact before layouts
    build); shorter tuples mean 'off' — pre-existing names and queue lines
    stay valid."""
    return v[10] if len(v) > 10 else "off"


def _vat(v):
    """Auto-tune flag of a variant tuple (12th field: 'sched' runs the
    fixed coarse->fine staleness anneal of tune.bench_schedule — K=4 from
    epoch 0, K=2 at 40%, K=1 at 70% — with each retune's rebuild + compile
    epochs excluded from the mean, the bench twin of run.py's `--tune`);
    shorter tuples mean 'off' — pre-existing names and queue lines stay
    valid."""
    return v[11] if len(v) > 11 else "off"


def _vname(v):
    """Candidate display/CLI name for a (spmm, use_pallas, gather_dtype,
    dense_dtype, tile[, halo[, overlap[, replicas[, feat[, refresh[,
    reorder[, autotune]]]]]]]) variant tuple — the vocabulary --candidates
    and .watch_queue lines are written in (unit-pinned so a rename can
    never silently invalidate a queued tunnel-window run)."""
    return (v[0] + ("+pallas" if v[1] else "")
            + ({"fp8": "+f8g", "int8": "+i8g"}.get(v[2], ""))
            + ("+i8d" if v[3] == "int8" else "")
            + (f"+t{v[4]}" if v[4] != 512 else "")
            + ({"ragged": "+rag", "shift": "+shift"}.get(_vhalo(v), ""))
            + ("+ovl" if _vovl(v) == "split" else "")
            + (f"+rep{_vrep(v)}" if _vrep(v) != 1 else "")
            + (f"+feat{_vfeat(v)}" if _vfeat(v) != 1 else "")
            + (f"+hr{_vhr(v)}" if _vhr(v) != 1 else "")
            + ("+ro" if _vro(v) != "off" else "")
            + ("+at" if _vat(v) != "off" else ""))


def _emit_result_line(args, value, status=None, measured_at=None, spmm=None,
                      measured_epoch=None):
    """The driver-parsed JSON line. Extra keys (status/measured_at/
    measured_epoch) label carried-forward numbers so they can't read as
    fresh measurements — and, conversely, let a reader verify HOW stale a
    carried value is (the numeric epoch stamp is written only by a real
    gated hardware measurement)."""
    line = {"metric": _metric_name(args),
            "value": round(value, 4) if value else None,
            "unit": "s/epoch"}
    if args.model == "graphsage":
        line["vs_baseline"] = (round(BASELINE_EPOCH_S / value, 3)
                               if value else None)
    if status:
        line["status"] = status
    if measured_at:
        line["measured_at"] = measured_at
    if spmm:
        line["spmm"] = spmm
    if isinstance(measured_epoch, (int, float)) and measured_epoch:
        # guarded: best_known.json is hand-editable and this line must
        # print before anything else can fail (a TypeError here would
        # reproduce the no-JSON artifact the supervisor exists to prevent)
        line["measured_epoch"] = measured_epoch
        line["measured_age_h"] = round((time.time() - measured_epoch) / 3600,
                                       1)
    print(json.dumps(line), flush=True)


def _probe_backend(timeout_s: float) -> str | None:
    """Initialize the JAX backend in a THROWAWAY subprocess (jax.devices()
    can hang forever when the axon tunnel is wedged — a timeout kill of a
    mere devices() probe has been safe, unlike mid-Pallas-compile kills).
    Returns the backend name or None."""
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout_s)
        if r.returncode == 0 and r.stdout.strip():
            return r.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return None


def _supervise(args) -> int:
    """Parent process: never touches the TPU backend itself, so it cannot
    hang or crash with it. Guarantees a parseable JSON line on stdout."""
    import subprocess
    t0 = time.time()
    deadline = t0 + args.hard_timeout_s
    known = _load_best_known(args) or {}
    log = lambda *a: print(*a, file=sys.stderr, flush=True)

    # 1) a valid line lands FIRST: any later kill still leaves parseable data
    _emit_result_line(args, known.get("value"), status="carried-forward",
                      measured_at=known.get("measured_at"),
                      spmm=known.get("spmm"),
                      measured_epoch=known.get("measured_epoch"))

    env = dict(os.environ, BNSGCN_BENCH_WORKER="1")
    attempt = 0
    fast_fails = 0
    while time.time() < deadline:
        # 2) liveness probe with backoff (bounded by --probe-budget-s per
        #    attempt cycle; UNAVAILABLE raises fast, a wedge hangs → kill)
        probe_end = min(deadline, time.time() + args.probe_budget_s)
        backend = None
        while time.time() < probe_end:
            backend = _probe_backend(args.probe_timeout_s)
            if backend:
                break
            log(f"  backend probe failed at +{time.time() - t0:.0f}s; "
                f"retrying in 60s")
            time.sleep(min(60, max(0, probe_end - time.time())))
        if backend is None:
            break
        if backend != "tpu" and args.scale >= 0.1 and not os.environ.get(
                "BNSGCN_BENCH_ALLOW_CPU"):
            # a full-scale run on the CPU fallback backend would report a
            # meaningless number; carried-forward hardware data is better
            log(f"  backend is {backend!r}, not tpu — refusing full-scale "
                f"run (set BNSGCN_BENCH_ALLOW_CPU=1 to override)")
            break
        # 3) the worker inherits stdout: its provisional/final JSON lines
        #    land after (and therefore outrank) the carried-forward line
        attempt += 1
        budget = max(60.0, deadline - time.time())
        log(f"  launching bench worker (attempt {attempt}, backend "
            f"{backend}, {budget:.0f}s left)")
        w0 = time.time()
        try:
            p = subprocess.Popen([sys.executable] + sys.argv, env=env)
            rc = p.wait(timeout=budget)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            log(f"  worker hit the hard timeout after {budget:.0f}s")
            rc = -9
        if rc == 0:
            return 0
        if rc == 2:
            # the worker's own argument validation (e.g. --candidates typo):
            # deterministic, relaunching would burn the whole TPU window
            log("  worker rejected its arguments (rc=2); not relaunching")
            return 2
        if rc in (75, 76, 77, 78):
            # resilience exit-code contract (README "Fault tolerance"):
            # preempted / diverged / hung / coordinated-abort carry meaning
            # the requeue wrapper (tools/tpu_watchdog5.sh handle_rc) acts
            # on — propagate instead of blindly relaunching into a
            # preempted chip or a deterministic divergence. The in-process
            # watchdog os._exit(77)s a hung worker, so this is also the
            # tunnel-outage path the deleted alive()-polling used to own.
            log(f"  worker exited with resilience code rc={rc}; "
                "propagating to the requeue wrapper")
            return rc
        log(f"  worker exited rc={rc}; "
            f"{max(0, deadline - time.time()):.0f}s of budget left")
        # a worker that dies fast (before graph gen + compile could finish)
        # is likely failing deterministically: back off so the relaunch loop
        # doesn't re-pay generation + 20-40s compiles back-to-back, and stop
        # after a few consecutive fast failures (round-3 advisor finding)
        if time.time() - w0 < 120:
            fast_fails += 1
            if fast_fails >= 3:
                log("  3 consecutive fast worker failures; giving up")
                break
            pause = min(120.0, 30.0 * fast_fails)
            log(f"  fast failure #{fast_fails}; backing off {pause:.0f}s")
            time.sleep(min(pause, max(0, deadline - time.time())))
        else:
            fast_fails = 0
    # 4) final fallback: report freshest known data with an honest status.
    # "partial" means a worker measured something during THIS supervisor run
    # and then failed; decided on the numeric measured_epoch stamp — the seed
    # entries (free-text measured_at, no measured_epoch) always classify as
    # tpu-unavailable (round-3 advisor finding: a lexicographic compare of
    # human-readable timestamps mislabeled never-measured seed data)
    fresh = _load_best_known(args) or {}
    last_meas = max(fresh.get("measured_epoch", 0) or 0,
                    fresh.get("last_measured_epoch", 0) or 0)
    status = "partial" if last_meas > t0 else "tpu-unavailable"
    _emit_result_line(args, fresh.get("value"), status=status,
                      measured_at=fresh.get("measured_at"),
                      spmm=fresh.get("spmm"),
                      measured_epoch=fresh.get("measured_epoch"))
    return 0


def _serve_dispatch(args) -> int:
    """--serve mode: measure ONLINE SERVING latency/throughput instead of
    training epoch time. Runs tools/serve_bench.py once per requested
    variant (serve1 = single-host server, serve2p = 2-part router-fronted
    fleet); the child inherits stdout, so its backend-count-tagged
    SERVE_METRICS JSON lines land in the same last-line-wins pipe the
    driver already captures. Host-side by construction (the serving tier
    is host numpy plus a one-shot table precompute), so this path skips
    the TPU supervisor/probe machinery entirely — there is no tunnel to
    babysit and nothing to carry forward."""
    import subprocess
    variants = {"serve1": [("serve1", 0)], "serve2p": [("serve2p", 2)],
                "both": [("serve1", 0), ("serve2p", 2)]}[args.serve]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "serve_bench.py")
    rc_worst = 0
    for variant, fleet in variants:
        cmd = [sys.executable, script, "--json-only",
               "--requests", str(args.serve_requests),
               "--concurrency", str(args.serve_concurrency),
               "--variant", variant]
        if fleet:
            cmd += ["--fleet", str(fleet)]
        print(f"serve bench: {variant} "
              + (f"({fleet} sharded backends + router)" if fleet
                 else "(single-host server)"), file=sys.stderr, flush=True)
        try:
            rc = subprocess.run(cmd, env=env,
                                timeout=args.budget_s).returncode
        except subprocess.TimeoutExpired:
            print(f"serve bench: {variant} hit the {args.budget_s:.0f}s "
                  f"budget; killed", file=sys.stderr, flush=True)
            rc = -9
        if rc != 0:
            print(f"serve bench: {variant} exited rc={rc}",
                  file=sys.stderr, flush=True)
            rc_worst = rc_worst or (rc if rc > 0 else 1)
    return rc_worst


def _features(label: np.ndarray, n_feat=602, n_class=41) -> np.ndarray:
    """Label-correlated features from a dedicated RNG stream — identical on
    cold and warm runs (the cache stores only edges/labels/masks)."""
    rng = np.random.default_rng(1234)
    centers = rng.normal(size=(n_class, n_feat)).astype(np.float32)
    return (centers[label] + rng.normal(
        scale=1.0, size=(label.shape[0], n_feat))).astype(np.float32)


def _cached_graph(n_nodes: int, avg_degree: int, cache_dir: str, log,
                  kind: str = "uniform"):
    """Synthetic graph with npz edge cache (generation dominates cold runs).

    kind='dcsbm': Reddit-calibrated degree-corrected SBM (41 communities,
    power-law degrees, edge homophily 0.78 — see
    data/graph.reddit_like_graph); 'uniform': the structure-free power-law
    graph (round-1 stand-in, kept as the no-locality worst case);
    'dcsbm-mid': the same SBM at homophily 0.45 — calibrated to put hybrid
    tile coverage in the 30-50%% band where --spmm auto's 0.5 threshold
    decides, so the flip point gets a measured point between the clustered
    (78.5%%) and uniform (21%%) extremes."""
    from bnsgcn_tpu.data.graph import Graph, reddit_like_graph, synthetic_graph
    os.makedirs(cache_dir, exist_ok=True)
    tag = {"uniform": "synth", "dcsbm": "dcsbm",
           "dcsbm-mid": "dcsbmmid"}[kind]
    path = os.path.join(cache_dir, f"{tag}_{n_nodes}_{avg_degree}.npz")
    if os.path.exists(path):
        log(f"loading cached graph {path}")
        z = np.load(path)
        label = z["label"].astype(np.int64)
        return Graph(n_nodes, z["src"].astype(np.int64), z["dst"].astype(np.int64),
                     _features(label), label, z["train"], z["val"], z["test"])
    t0 = time.time()
    if kind == "uniform":
        g = synthetic_graph(n_nodes=n_nodes, avg_degree=avg_degree, n_feat=602,
                            n_class=41, seed=0, power_law=True)
    elif kind == "dcsbm-mid":
        g = reddit_like_graph(n_nodes=n_nodes, avg_degree=avg_degree,
                              n_feat=8, seed=0, homophily=0.45)
    else:
        g = reddit_like_graph(n_nodes=n_nodes, avg_degree=avg_degree,
                              n_feat=8, seed=0)
    g.feat = _features(g.label)
    log(f"  graph generated in {time.time() - t0:.1f}s: {g.n_edges} edges")
    np.savez(path, src=g.src.astype(np.int32), dst=g.dst.astype(np.int32),
             label=g.label.astype(np.int32),
             train=g.train_mask, val=g.val_mask, test=g.test_mask)
    return g


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--scale", type=float, default=0.5,
                    help="fraction of Reddit's 232,965 nodes per chip (0.5 = rank share at P=2)")
    ap.add_argument("--avg-degree", type=int, default=492,
                    help="mean degree (Reddit: 114.6M edges / 233k nodes ~= 492)")
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--dtype", choices=["f32", "bf16"], default="bf16")
    ap.add_argument("--graph", choices=["dcsbm", "uniform", "dcsbm-mid"],
                    default="dcsbm",
                    help="dcsbm: Reddit-calibrated clustered stand-in "
                         "(default); uniform: structure-free worst case")
    ap.add_argument("--spmm", choices=["hybrid", "ell"], default="hybrid")
    ap.add_argument("--model", choices=["graphsage", "gat"],
                    default="graphsage",
                    help="gat: 2-head ELL-attention GAT on the same graph "
                         "(reference module/model.py:102-132; measures the "
                         "edge-softmax hot loop, which no SpMM variant "
                         "touches — candidates collapse to the anchor)")
    ap.add_argument("--occupancy", type=int, default=0,
                    help="hybrid: min edges per tile to densify "
                         "(0 = auto: the tile's byte break-even, "
                         "tile*tile/512 — 512 for 512x512, 128 for +t256)")
    ap.add_argument("--tile-budget-mb", type=int, default=2048,
                    help="hybrid: int8 dense-tile HBM budget per direction")
    ap.add_argument("--skip-anchor", action="store_true",
                    help="gate against the stored anchor losses in "
                         "best_known.json instead of re-measuring the ell "
                         "anchor (short tunnel windows; falls back to "
                         "measuring when nothing is stored)")
    ap.add_argument("--no-pallas", action="store_true",
                    help="skip the Pallas candidate (the axon remote "
                         "compiler has wedged the TPU tunnel when killed "
                         "mid-compile; measurement sessions run it last, "
                         "separately)")
    ap.add_argument("--cache-dir", type=str,
                    default=os.environ.get("BNSGCN_CACHE_DIR")
                    or "./bench_cache",
                    help="artifact/layout/best-known cache dir (default "
                         "$BNSGCN_CACHE_DIR or ./bench_cache; point it at a "
                         "persistent volume to survive container wipes)")
    ap.add_argument("--profile-dir", type=str, default="",
                    help="diagnostic: write a jax.profiler trace of each "
                         "measured candidate's first epoch chunk to "
                         "<dir>/<candidate>/ (parse with tools/trace_comm.py "
                         "--parse --breakdown). Traced timings are never "
                         "recorded to best_known.json")
    ap.add_argument("--json-only", action="store_true")
    ap.add_argument("--prep-only", action="store_true",
                    help="build + disk-cache artifacts and SpMM layouts, "
                         "then exit (CPU prep while the TPU is idle/down)")
    ap.add_argument("--budget-s", type=float, default=1500.0,
                    help="soft wall-clock budget: skip remaining SpMM "
                         "candidates once exceeded (the JSON line always "
                         "reports the best measured so far)")
    ap.add_argument("--candidates", type=str, default="",
                    help="comma list restricting/ordering the SpMM variants "
                         "to measure after the ell anchor (names as logged: "
                         "hybrid, hybrid+i8g+i8d, hybrid+f8g+i8d, hybrid+f8g, "
                         "ell+i8g, ell+f8g, hybrid+pallas, hybrid+pallas+i8g; "
                         "a +rag suffix runs the same recipe under the "
                         "exact-bytes ragged halo exchange: hybrid+rag, "
                         "ell+rag, hybrid+pallas+rag; a +ovl suffix runs it "
                         "with --overlap split interior/frontier "
                         "aggregation: hybrid+ovl, ell+ovl, "
                         "hybrid+pallas+ovl, hybrid+pallas+rag+ovl; a +repN "
                         "suffix runs it on an (N, 1) replica mesh — N "
                         "independently-BNS-sampled replicas, fused "
                         "cross-replica gradient mean, needs N devices: "
                         "hybrid+rep2, ell+rep2, hybrid+pallas+rep2, "
                         "hybrid+pallas+rag+ovl+rep2; a +featT suffix "
                         "shards hidden dims T-ways on the innermost feat "
                         "axis — H/T halo payloads, one psum per layer, "
                         "needs T devices: hybrid+feat2, ell+feat2, "
                         "hybrid+pallas+feat2, hybrid+pallas+rag+ovl+feat2; "
                         "a +hrK suffix reuses cached halos for up to K "
                         "epochs (--halo-refresh K staleness-bounded "
                         "refresh, ~1/K steady-state wire bytes): "
                         "hybrid+pallas+hr2, hybrid+pallas+hr4, "
                         "hybrid+pallas+rag+ovl+hr4; a +ro suffix bakes "
                         "the --reorder cluster LPA+FFD row permutation "
                         "into the artifact before layouts build — higher "
                         "dense-tile coverage on low-locality graphs: "
                         "hybrid+ro, hybrid+t256+ro, hybrid+pallas+ro, "
                         "hybrid+pallas+t256+ro; a +at suffix runs the "
                         "closed-loop staleness anneal (tune.bench_schedule"
                         ": K=4 from epoch 0, K=2 at 40%, K=1 at 70%, "
                         "retune rebuilds untimed): hybrid+pallas+at)"
                         " — for short TPU-tunnel windows. The pallas names "
                         "only exist on a TPU backend without --no-pallas; "
                         "an all-unknown list is an error (exit 2), never a "
                         "silent anchor-only run")
    ap.add_argument("--obs-log", type=str,
                    default=os.environ.get("BNSGCN_OBS_LOG", ""),
                    help="obs telemetry JSONL (bnsgcn_tpu/obs.py): the "
                         "worker records a bench header + one bench_variant "
                         "event per gated measurement, and every result "
                         "JSON carries the log's path — hardware-window "
                         "runs become post-hoc auditable with "
                         "tools/obs_report.py --compare")
    ap.add_argument("--serve", choices=["off", "serve1", "serve2p", "both"],
                    default="off",
                    help="measure online serving instead of epoch time: "
                         "run tools/serve_bench.py per variant (serve1 = "
                         "single-host server, serve2p = 2-part router-"
                         "fronted fleet; both = the comparison pair) and "
                         "emit backend-count-tagged SERVE_METRICS lines "
                         "through the same driver pipe")
    ap.add_argument("--serve-requests", type=int, default=200,
                    help="--serve: timed requests per tier per variant")
    ap.add_argument("--serve-concurrency", type=int, default=4,
                    help="--serve: concurrent client threads")
    ap.add_argument("--probe-timeout-s", type=float, default=150.0,
                    help="supervisor: per-probe subprocess timeout (a "
                         "wedged tunnel HANGS jax.devices() forever)")
    ap.add_argument("--probe-budget-s", type=float, default=480.0,
                    help="supervisor: total probe+backoff time per worker "
                         "attempt before giving up on the backend")
    ap.add_argument("--hard-timeout-s", type=float, default=None,
                    help="supervisor: total wall budget incl. worker "
                         "relaunches (default: --budget-s + 1500)")
    args = ap.parse_args()
    if args.hard_timeout_s is None:
        args.hard_timeout_s = args.budget_s + 1500.0
    t_start = time.time()

    if args.serve != "off":
        # serving bench: dispatched BEFORE the supervisor re-exec — the
        # children run on the host platform and must not inherit the
        # worker env / TPU probe lifecycle
        sys.exit(_serve_dispatch(args))

    if not args.prep_only and os.environ.get("BNSGCN_BENCH_WORKER") != "1":
        sys.exit(_supervise(args))

    if args.prep_only:
        # prep is pure host numpy — never touch the TPU for it. (If the
        # axon tunnel is WEDGED, the sitecustomize hangs at interpreter
        # start, before this line: launch with PALLAS_AXON_POOL_IPS= then.)
        os.environ["JAX_PLATFORMS"] = "cpu"
    # +repN / +featT candidates need N x T devices (the bench mesh is
    # (replicas, 1 part, feat)). The flag below only ever affects the host
    # (CPU) platform — free virtual devices for smoke/preflight runs — and
    # must be set BEFORE jax initializes; a TPU backend ignores it, and a
    # 1-chip TPU window simply fails the repN/featT candidate into the
    # fallback path (logged), never the whole run. A full (no --candidates)
    # run uses UNIVERSE_MAX_DEVICES: keep it == the largest replicas*feat
    # product in the `universe` tuples below (it cannot be derived from the
    # list here — the list is built after `import jax`, and this flag must
    # precede it).
    UNIVERSE_MAX_DEVICES = 2
    import re as _re
    _needs = []
    for _nm in args.candidates.split(","):
        _r = _re.search(r"\+rep(\d+)", _nm)
        _f = _re.search(r"\+feat(\d+)", _nm)
        _needs.append((int(_r.group(1)) if _r else 1)
                      * (int(_f.group(1)) if _f else 1))
    _max_dev = (max(_needs) if args.candidates
                else UNIVERSE_MAX_DEVICES)
    if _max_dev > 1 and "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={_max_dev}").strip()
    import jax

    if args.prep_only or os.environ.get("JAX_PLATFORMS"):
        # an explicit JAX_PLATFORMS request (e.g. cpu smoke runs with
        # BNSGCN_BENCH_ALLOW_CPU) must also beat the sitecustomize pin —
        # otherwise the worker's default_backend() call probes the axon
        # tunnel and hangs when it is down
        from bnsgcn_tpu.utils.platform import honor_platform_request
        honor_platform_request(strict=args.prep_only)
    try:
        # persistent XLA compilation cache: repeat bench runs (and reruns
        # after a tunnel drop) skip the 20-40s compiles when the program is
        # unchanged; harmless no-op where the backend ignores it
        cc_dir = os.path.join(args.cache_dir, "xla_cache")
        os.makedirs(cc_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cc_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception as ex:           # pragma: no cover
        print(f"  compilation cache unavailable: {ex}", file=sys.stderr)
    import jax.numpy as jnp

    from bnsgcn_tpu.config import Config
    from bnsgcn_tpu.data.artifacts import build_artifacts
    from bnsgcn_tpu.data.partitioner import partition_graph
    from bnsgcn_tpu.models.gnn import ModelSpec, init_params
    from bnsgcn_tpu.parallel.mesh import make_parts_mesh
    from bnsgcn_tpu.parallel.replicas import make_mesh
    from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                    init_training, place_blocks, place_replicated)

    log = (lambda *a: None) if args.json_only else (lambda *a: print(*a, file=sys.stderr))

    # ell runs FIRST as the trusted reference; other variants must agree
    # with its FIRST-step loss (guards a silently-miscompiling kernel from
    # ever winning the headline; step-0 comparison keeps legitimately-lossy
    # variants like fp8 gathers from accumulating drift over --epochs)
    # main contenders first so a tight budget still measures them; the
    # universe is independent of --spmm so --candidates can always select
    # from the full documented name set. Candidate validation runs HERE,
    # before graph generation + artifact build, so a --candidates typo
    # exits in seconds instead of burning minutes of cold prep first.
    # variant = (spmm, use_pallas, gather_dtype, dense_dtype, tile).
    # MEASURED WINNERS FIRST (v5e 2026-07-30: hybrid+pallas 0.573 s/epoch,
    # hybrid 0.87, ell 1.67, i8g/f8g reduce-path variants lose) so a
    # budget-starved window still measures the best known before exploring.
    # tpu_codepaths: also true under BNSGCN_BENCH_PREFLIGHT=1, so a CPU
    # preflight can select the exact queued pallas candidate names (their
    # kernel bodies fall back to the XLA twins off-TPU; everything else —
    # layouts, tile stacks, unroll accumulation, gates — runs for real)
    from bnsgcn_tpu.utils.platform import tpu_codepaths
    pallas_ok = tpu_codepaths() and not args.no_pallas
    universe = []
    if pallas_ok:
        universe += [("hybrid", True, "native", "native", 512),
                     # finer tiles: 4x tiles/budget-byte, less ELL residual
                     ("hybrid", True, "native", "native", 256),
                     # fused Pallas dense + 1-byte int8-unroll residual rows
                     ("hybrid", True, "int8", "native", 512),
                     ("hybrid", True, "int8", "native", 256),
                     # int8 slabs inside the fused kernel (int8 MXU, one
                     # per-call scale) — alone and with int8 residual rows
                     ("hybrid", True, "native", "int8", 512),
                     ("hybrid", True, "int8", "int8", 512),
                     # the full-lever endgame: finer tiles + int8 residual
                     # rows + int8 slabs (queued for when the single-lever
                     # lines confirm their independent wins)
                     ("hybrid", True, "int8", "int8", 256)]
    if pallas_ok:
        # exact-bytes ragged halo exchange under the headline recipe: on the
        # single bench chip this measures the ragged collective's dispatch
        # cost inside the real train step (cross-chip bytes need a pod);
        # ragged_all_to_all itself is v5e-validated (hw_session_r4.log)
        universe += [("hybrid", True, "native", "native", 512, "ragged"),
                     # interior/frontier split aggregation (--overlap split):
                     # a single bench chip measures the split-layout overhead
                     # (P=1 has zero frontier rows); the latency hiding
                     # itself needs a multi-chip window
                     ("hybrid", True, "native", "native", 512, "padded",
                      "split"),
                     ("hybrid", True, "native", "native", 512, "ragged",
                      "split"),
                     # replica-axis hybrid parallelism: 2 independently-
                     # BNS-sampled graph replicas on a (2, 1) mesh with the
                     # fused cross-replica gradient mean — needs >= 2 chips
                     # (a 1-chip window falls back and logs); measures the
                     # variance-reduction recipe's wall-clock cost
                     ("hybrid", True, "native", "native", 512, "padded",
                      "off", 2),
                     ("hybrid", True, "native", "native", 512, "ragged",
                      "split", 2),
                     # feat/tensor axis (parallel/feat.py): hidden dims
                     # sharded 2-ways on a (1, 1, 2) mesh — measures the
                     # per-layer feat-psum + sliced-SpMM recipe on 2 chips
                     # (the T x halo-byte win itself needs a multi-part pod);
                     # wide-hidden (--hidden 512) is where it should win
                     ("hybrid", True, "native", "native", 512, "padded",
                      "off", 1, 2),
                     ("hybrid", True, "native", "native", 512, "ragged",
                      "split", 1, 2),
                     # staleness-bounded halo refresh (--halo-refresh K):
                     # steady-state epochs redraw only chunk epoch%K of each
                     # boundary set and reuse the cached rows elsewhere. On
                     # the single bench chip this measures the cached step's
                     # compute cost (plan + where-combine overhead); the
                     # ~K x wire-byte win itself needs a multi-part pod
                     ("hybrid", True, "native", "native", 512, "padded",
                      "off", 1, 1, 2),
                     ("hybrid", True, "native", "native", 512, "padded",
                      "off", 1, 1, 4),
                     ("hybrid", True, "native", "native", 512, "ragged",
                      "split", 1, 1, 4),
                     # graph reordering (--reorder cluster): the LPA+FFD
                     # artifact permutation raises dense-tile coverage
                     # before layouts build — the uniform/dcsbm-mid graph
                     # twins in .watch_queue are the headline targets
                     ("hybrid", True, "native", "native", 512, "padded",
                      "off", 1, 1, 1, "cluster"),
                     ("hybrid", True, "native", "native", 256, "padded",
                      "off", 1, 1, 1, "cluster"),
                     # closed-loop staleness anneal (--tune / tune.py): the
                     # fixed coarse->fine schedule K=4 -> 2 -> 1 with each
                     # retune's rebuild+compile epochs untimed — measures
                     # what a tuned run's STEADY epochs cost vs the static
                     # +hrK points on either side of the anneal
                     ("hybrid", True, "native", "native", 512, "padded",
                      "off", 1, 1, 1, "off", "sched")]
    universe += [("hybrid", False, "native", "native", 512),
                 ("hybrid", False, "native", "native", 256),
                 ("hybrid", False, "native", "int8", 512),
                 ("hybrid", False, "int8", "int8", 512),
                 ("hybrid", False, "fp8", "int8", 512),
                 ("hybrid", False, "fp8", "native", 512),
                 ("ell", False, "int8", "native", 512),
                 ("ell", False, "fp8", "native", 512),
                 ("hybrid", False, "native", "native", 512, "ragged"),
                 ("ell", False, "native", "native", 512, "ragged"),
                 ("hybrid", False, "native", "native", 512, "padded",
                  "split"),
                 ("hybrid", False, "native", "native", 512, "ragged",
                  "split"),
                 ("ell", False, "native", "native", 512, "padded", "split"),
                 ("hybrid", False, "native", "native", 512, "padded",
                  "off", 2),
                 ("ell", False, "native", "native", 512, "padded", "off", 2),
                 ("hybrid", False, "native", "native", 512, "padded",
                  "off", 1, 2),
                 ("ell", False, "native", "native", 512, "padded",
                  "off", 1, 2),
                 # CPU-measurable reorder twins of the pallas +ro entries
                 ("hybrid", False, "native", "native", 512, "padded",
                  "off", 1, 1, 1, "cluster"),
                 ("hybrid", False, "native", "native", 256, "padded",
                  "off", 1, 1, 1, "cluster")]
    anchor = ("ell", False, "native", "native", 512)
    if args.spmm == "hybrid":
        candidates = [anchor] + universe
    else:
        candidates = [(args.spmm, False, "native", "native", 512)]

    if args.candidates:
        by_name = {_vname(v): v for v in universe}
        candidates = [anchor]
        picked = []
        for nm in args.candidates.split(","):
            nm = nm.strip()
            if nm and nm in by_name:
                picked.append(by_name[nm])
            elif nm:
                # unconditional stderr: under --json-only `log` is a no-op
                # and a silently-ignored selection would be invisible
                print(f"  unknown candidate {nm!r} (known: "
                      f"{sorted(by_name)}); ignoring", file=sys.stderr)
        if not picked:
            # all-unknown is a typo, and a silent anchor-only run would burn
            # a short TPU window; exit 2 = deterministic argument error (the
            # supervisor recognizes it and does NOT relaunch)
            print(f"  --candidates {args.candidates!r} matched no known "
                  f"variant (known: {sorted(by_name)}); exiting",
                  file=sys.stderr)
            sys.exit(2)
        candidates = candidates[:1] + picked

    if args.model == "gat":
        # GAT's hot loop is the dense per-row ELL attention (edge softmax +
        # weighted combine), which no SpMM candidate touches — the matrix
        # collapses to the single anchor-shaped run and the measurement IS
        # the GAT epoch time (reference module/model.py:102-132; BNS note
        # train.py:117: GAT halos ride ratio=1)
        if args.candidates:
            log("  --model gat ignores --candidates (SpMM variants do not "
                "apply to the attention path)")
        candidates = [anchor]
    n_nodes = max(int(232_965 * args.scale), 2000)
    model_desc = ("GAT(2 heads)" if args.model == "gat" else "GraphSAGE")
    log(f"workload: {n_nodes} nodes x mean degree {args.avg_degree} "
        f"(~{n_nodes * args.avg_degree / 1e6:.1f}M edges/chip), "
        f"{model_desc} {args.layers}x{args.hidden}, pp, dtype={args.dtype}, "
        f"graph={args.graph}, spmm={args.spmm}")
    g = _cached_graph(n_nodes, args.avg_degree, args.cache_dir, log,
                      kind=args.graph)

    t0 = time.time()
    tag = f"{args.graph}_{n_nodes}_{args.avg_degree}"
    art = _disk_cached(
        os.path.join(args.cache_dir, f"art_{tag}.pkl"),
        lambda: build_artifacts(g, partition_graph(g, 1)), log)
    log(f"  artifacts in {time.time() - t0:.1f}s")
    sizes = (art.n_feat,) + (args.hidden,) * (args.layers - 1) + (art.n_class,)
    spec = ModelSpec(args.model, sizes, norm="layer", dropout=0.5,
                     use_pp=True, train_size=art.n_train,
                     heads=2 if args.model == "gat" else 1)
    mesh = make_parts_mesh(1)
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    # graftlint: disable=prng-literal-key(fixed bench keys: every variant times the same sample stream)
    skey, dkey = jax.random.key(0), jax.random.key(1)

    def make_cfg(variant):
        spmm, use_pallas, gather, dense, tile = variant[:5]
        cfg = Config(model=args.model,
                      halo_exchange=_vhalo(variant),
                      overlap=_vovl(variant),
                      replicas=_vrep(variant),
                      feat=_vfeat(variant),
                      halo_refresh=_vhr(variant),
                      reorder=_vro(variant),
                      heads=2 if args.model == "gat" else 1,
                      n_layers=args.layers,
                      n_hidden=args.hidden, use_pp=True, dropout=0.5,
                      lr=0.01, sampling_rate=0.1, spmm=spmm,
                      use_pallas=use_pallas, spmm_gather=gather,
                      spmm_dense=dense,
                      block_occupancy=args.occupancy,
                      block_tile_budget_mb=args.tile_budget_mb,
                      block_tile=tile,
                      n_feat=art.n_feat, n_class=art.n_class,
                      n_train=art.n_train)
        if _vat(variant) != "off":
            # +at starts at the anneal's epoch-0 point (K=4) so the first
            # compile already targets the coarse geometry — never a
            # throwaway build, exactly run.py's startup fold
            from bnsgcn_tpu import tune as tune_mod
            for _ep, _ch in tune_mod.bench_schedule(args.epochs):
                if _ep == 0:
                    cfg = cfg.replace(**_ch)
        return cfg

    # graftperf (analysis/perf) predictions per candidate name — filled by
    # setup_and_compile from the BUILT layout, joined to the measurement
    # in the gated loop below so every bench record doubles as
    # calibration data (predicted_step_s / predicted_wire_mb + the
    # residual log line)
    perf_pred = {}

    # +ro candidates run on the PERMUTED artifact (what run.py's
    # maybe_reorder produces) — the perm depends on the tile size, so
    # memoize one reordered artifact per tile value across candidates
    ro_arts = {}

    def art_for(variant):
        if _vro(variant) == "off":
            return art
        tile = variant[4]
        if tile not in ro_arts:
            from bnsgcn_tpu.data.reorder import apply_reorder, compute_orders
            t0 = time.time()
            ro_arts[tile] = apply_reorder(art, compute_orders(art,
                                                              tile_r=tile))
            log(f"  reorder: t{tile} order built in {time.time() - t0:.1f}s")
        return ro_arts[tile]

    def setup_and_compile(variant):
        """Layouts + device data + the first (compiling) train step — any
        failure here on real hardware triggers the ELL fallback."""
        t0 = time.time()
        spmm = variant[0]
        cfg = make_cfg(variant)
        v_art = art_for(variant)
        # +repN/+featT candidates compile onto their own (N, 1, T) mesh; the
        # layout cache is mesh-independent so the stacks are still shared
        mesh = make_mesh(1, _vrep(variant), _vfeat(variant))
        fns, hspec, tables, tables_full = build_step_fns(
            cfg, spec, v_art, mesh, layout_cache=layout_cache)
        if spmm == "hybrid":
            from bnsgcn_tpu.ops.block_spmm import dense_edge_count
            dc = dense_edge_count(fns.extra_blk)
            log(f"  hybrid tiling: {dc / 1e6:.1f}M of "
                f"{g.n_edges / 1e6:.1f}M edges in dense tiles "
                f"({dc / g.n_edges:.0%})")
        log(f"  {spmm} layouts in {time.time() - t0:.1f}s")
        # roofline prediction from the layout that actually built (tile
        # stacks, ELL geometry, halo geometry) — best-effort: a prediction
        # failure must never cost a tunnel-window measurement
        try:
            from bnsgcn_tpu.analysis.perf import calibration as pcal
            from bnsgcn_tpu.analysis.perf import model as pmod
            table = pcal.backend_table(pcal.load_calibration(),
                                       jax.default_backend())
            nbytes = 2 if cfg.dtype == "bfloat16" else 4
            gb = {"int8": 1, "fp8": 1}.get(variant[2], nbytes)
            slots_full = 0.0
            tiles = 0
            if v_art.ell_geometry:
                slots_full = 0.5 * (
                    pmod.ell_geometry_slots(v_art.ell_geometry, "fwd")
                    + pmod.ell_geometry_slots(v_art.ell_geometry, "bwd"))
            fill = (v_art.pad_edges / slots_full) if slots_full else 0.74
            if spmm == "hybrid":
                from bnsgcn_tpu.ops.block_spmm import dense_edge_count
                dcov = dense_edge_count(fns.extra_blk) / max(g.n_edges, 1)
                for tkey in ("blk_tiles_fwd", "int_blk_tiles_fwd",
                             "fro_blk_tiles_fwd"):
                    t_arr = fns.extra_blk.get(tkey)
                    if t_arr is not None:
                        tiles += int(np.asarray(t_arr).shape[1])
                slots = (v_art.pad_edges * max(1.0 - dcov, 0.0)
                         / max(fill, 1e-9))
            else:
                slots = slots_full or float(v_art.pad_edges)
            width = max(cfg.n_hidden // max(_vfeat(variant), 1), 1)
            wire_mb = pmod.steady_wire_mb(
                v_art.n_b, v_art.pad_boundary, cfg.sampling_rate,
                strategy=_vhalo(variant), wire="native",
                refresh=_vhr(variant), width=width,
                native_bytes=nbytes) * 2 * max(cfg.n_layers - 1, 1)
            feat_p = pmod.StepFeatures(
                n_apps=2 * int(cfg.n_layers), gather_slots=float(slots),
                row_bytes=int(cfg.n_hidden) * gb,
                gather_path="materialize",
                dense_tiles=tiles, tile=int(variant[4]),
                dense_path=(("pallas" if variant[1] else "xla")
                            if tiles else "none"),
                wire_mb=wire_mb)
            perf_pred[_vname(variant)] = {
                "predicted_step_s": round(
                    pmod.predict_step_s(feat_p, table), 4),
                "predicted_wire_mb": round(wire_mb, 4)}
        except Exception as ex:  # pragma: no cover - prediction is optional
            log(f"  [perf] prediction unavailable for {_vname(variant)}: "
                f"{type(ex).__name__}: {ex}")
        blk_np = build_block_arrays(v_art, spec.model)
        blk_np.update(fns.extra_blk)
        for k in fns.drop_blk_keys:
            blk_np.pop(k, None)
        blk = place_blocks(blk_np, mesh)
        tables_d = place_replicated(tables, mesh)
        pp_out = fns.precompute(
            blk, place_replicated(tables_full, mesh)).astype(dtype)
        if args.model == "gat":
            # GAT keeps raw features (cast to the compute dtype like
            # run.py:173 — an f32 blk['feat'] would silently measure 2x
            # the layer-0 feature HBM) and caches the full-rate extended
            # feature slab for the attention source side (run.py:177-181)
            blk["feat"] = blk["feat"].astype(dtype)
            blk["feat0_ext"] = pp_out
        else:
            blk["feat"] = pp_out
        # graftlint: disable=prng-literal-key(fixed seed: bench variants must share identical params)
        params, state = init_params(jax.random.key(0), spec, dtype=dtype)
        if _vfeat(variant) > 1:
            # feat-sharded weights (parallel/feat.py regex rules); the init
            # itself is the same host tree, so losses stay gate-comparable
            from bnsgcn_tpu.parallel import feat as feat_mod
            params = feat_mod.place_params(params, mesh, spec)
        else:
            params = place_replicated(params, mesh)
        state = place_replicated(state, mesh)
        _, _, opt = init_training(cfg, spec, mesh)
        log("compiling + warmup...")
        t0 = time.time()
        cache, tables_r_d = None, None
        if fns.train_step_full is not None:
            # +hrK: epoch 0 is the full-refresh step (historical exchange
            # geometry, same loss as fns.train_step — the step-0 gate below
            # stays meaningful) and seeds the halo cache the measured
            # steady-state epochs reuse
            tables_r_d = place_replicated(fns.tables_refresh, mesh)
            params, state, opt, loss, cache = fns.train_step_full(
                params, state, opt, jnp.uint32(0), blk, tables_d, skey, dkey)
        else:
            params, state, opt, loss = fns.train_step(
                params, state, opt, jnp.uint32(0), blk, tables_d, skey, dkey)
        log(f"  first step (compile) {time.time() - t0:.1f}s, "
            f"loss={float(loss):.4f}")
        from bnsgcn_tpu.utils.timers import estimate_static_hbm
        hbm = estimate_static_hbm([blk], [params, opt, state])
        ctx = {"cfg": cfg}

        def rebuild(changes):
            """+at retune: rebuild the step fns under changed comm levers.
            The shared layout cache absorbs the SpMM layout work (its keys
            do not depend on any tuned lever), so a retune costs one
            build + one compile — the same contract as run.py's --tune."""
            ctx["cfg"] = ctx["cfg"].replace(**changes)
            f2, _h2, tb2, tbf2 = build_step_fns(
                ctx["cfg"], spec, v_art, mesh, layout_cache=layout_cache)
            tr2 = (place_replicated(f2.tables_refresh, mesh)
                   if f2.tables_refresh is not None else None)
            return f2, place_replicated(tb2, mesh), tr2

        return (fns, blk, tables_d, params, state, opt, loss, cache,
                tables_r_d, rebuild, hbm)

    def measure(built, name="run", at_sched=None):
        """Timed epochs; chains CHUNK epochs between host syncs so the
        ~50-80ms tunnel round-trip amortizes out (matches the reference's
        free-running epoch loop). Under --profile-dir the FIRST chunk is
        traced (device-lane op breakdown); its timing includes profiler
        overhead, which is why traced runs never update best_known.
        `at_sched` ({epoch: lever changes}, +at candidates only) retunes
        the comm stack mid-run: the rebuild and its compile epochs are
        REAL training steps (the loss trajectory continues through them)
        but run untimed, the same compile-exclusion every other candidate
        gets for its first step."""
        (fns, blk, tables_d, params, state, opt, loss, cache,
         tables_r, rebuild, _) = built
        use_refresh = cache is not None
        at_sched = dict(at_sched or {})
        CHUNK = 4
        total_t, min_t = 0.0, float("inf")
        timed_n = 0
        e = 1

        def _untimed_cached():
            # the steady-state (cached) step compiles on ITS first call —
            # run it once untimed so +hrK/+at candidates get the same
            # compile-excluded treatment as everyone else (whose only
            # compile happened in setup_and_compile)
            nonlocal params, state, opt, loss, cache, e
            params, state, opt, loss, cache = fns.train_step_cached(
                params, state, opt, jnp.uint32(e), blk, tables_r, cache,
                skey, dkey)
            _ = float(loss)
            e += 1

        if use_refresh:
            _untimed_cached()
        tracing = False
        if args.profile_dir:
            jax.profiler.start_trace(os.path.join(
                args.profile_dir, name.replace("+", "_")))
            tracing = True
        try:
            while e <= args.epochs:
                due = sorted(ep for ep in at_sched if ep <= e)
                if due:
                    # +at retune boundary: fold every due entry, rebuild,
                    # and pay the full-refresh + compile epochs untimed
                    changes = {}
                    for ep in due:
                        changes.update(at_sched.pop(ep))
                    log("  at: epoch %d retune -> %s" % (e, " ".join(
                        f"{k}={v}" for k, v in sorted(changes.items()))))
                    fns, tables_d, tables_r = rebuild(changes)
                    use_refresh = fns.train_step_full is not None
                    if use_refresh:
                        params, state, opt, loss, cache = fns.train_step_full(
                            params, state, opt, jnp.uint32(e), blk, tables_d,
                            skey, dkey)
                        _ = float(loss)
                        e += 1
                        if e <= args.epochs:
                            _untimed_cached()
                    else:
                        cache = None
                        params, state, opt, loss = fns.train_step(
                            params, state, opt, jnp.uint32(e), blk, tables_d,
                            skey, dkey)
                        _ = float(loss)
                        e += 1
                    continue
                n = min(CHUNK, args.epochs - e + 1)
                nxt = min((ep for ep in at_sched), default=None)
                if nxt is not None and nxt > e:
                    # never time across a retune boundary
                    n = min(n, nxt - e)
                t0 = time.perf_counter()
                for _ in range(n):
                    if use_refresh:
                        params, state, opt, loss, cache = \
                            fns.train_step_cached(
                                params, state, opt, jnp.uint32(e), blk,
                                tables_r, cache, skey, dkey)
                    else:
                        params, state, opt, loss = fns.train_step(
                            params, state, opt, jnp.uint32(e), blk, tables_d,
                            skey, dkey)
                    e += 1
                _ = float(loss)   # force device sync through the host read
                dt = time.perf_counter() - t0
                if tracing:
                    # after dt: trace serialization must not inflate the
                    # first chunk's timing (round-4 advisor finding)
                    jax.profiler.stop_trace()
                    tracing = False
                total_t += dt
                timed_n += n
                min_t = min(min_t, dt / n)
        finally:
            if tracing:           # exception mid-measure: never leak the
                jax.profiler.stop_trace()   # trace into the next candidate
        if timed_n == 0:
            # a tiny-epoch +at run (e.g. the preflight's --epochs 2
            # override) can spend EVERY epoch on retune/compile
            # boundaries; time one extra epoch so the result line always
            # carries a real measurement instead of dividing by zero
            t0 = time.perf_counter()
            if use_refresh:
                params, state, opt, loss, cache = fns.train_step_cached(
                    params, state, opt, jnp.uint32(e), blk, tables_r,
                    cache, skey, dkey)
            else:
                params, state, opt, loss = fns.train_step(
                    params, state, opt, jnp.uint32(e), blk, tables_d,
                    skey, dkey)
            _ = float(loss)
            total_t, timed_n = time.perf_counter() - t0, 1
        if min_t == float("inf"):     # --epochs 1 +hrK: warmup ate the run
            min_t = total_t / max(timed_n, 1)
        return total_t / max(timed_n, 1), min_t, loss

    best, ref_loss, ref_final = None, None, None
    # step-0 / final losses of the NATIVE (unquantized) run of each SpMM
    # base: quantized variants gate against their native twin at 5% — far
    # tighter than the old blanket 10%-vs-ell gate, which was wide enough
    # to let a miscompiled int8 kernel win the headline (round-2 advisor)
    native_l0, native_lf = {}, {}
    if (args.skip_anchor and len(candidates) > 1
            and candidates[0] == anchor):
        # never skip when the anchor is the only candidate (a run must
        # measure something), and only against losses recorded under the
        # SAME loss-relevant knobs (anchor_lf depends on --epochs etc.)
        stored = _load_tag_entry(args) or {}
        if (stored.get("anchor_l0") is not None
                and stored.get("anchor_cfg") == _anchor_cfg(args)):
            ref_loss = float(stored["anchor_l0"])
            ref_final = float(stored["anchor_lf"])
            # the stored anchor IS ell's native twin: keep the tight 5%
            # twin gate for ell+i8g/+f8g picks instead of the 7% fallback
            native_l0["ell"], native_lf["ell"] = ref_loss, ref_final
            candidates = candidates[1:]
            log(f"  anchor skipped (stored l0={ref_loss:.4f} "
                f"lf={ref_final:.4f})")
        else:
            log("  --skip-anchor: no stored anchor losses for this "
                "workload+config; measuring the anchor")
    # share built layouts across candidates AND across runs (disk): keys
    # come from trainer.hybrid_layout_key so they cannot drift. The ell
    # layouts don't depend on the hybrid tuning knobs, so they get their
    # own file and survive occupancy/budget/tile sweeps; each hybrid
    # tiling geometry gets its own file (multi-GB stacks — one file per
    # key avoids rewriting every stack when one is added).
    from bnsgcn_tpu.trainer import (ell_layout_key, hybrid_layout_key,
                                    hybrid_tiling)

    def variant_key(variant):
        return (ell_layout_key(make_cfg(variant))
                if variant[0] != "hybrid"
                else hybrid_layout_key(make_cfg(variant)))

    def hyb_path_for(variant):
        occ, tile, budget = hybrid_tiling(make_cfg(variant))
        suf = f"_t{tile}" if tile != 512 else ""
        if _vovl(variant) == "split":
            suf += "_ovl"          # interior/frontier pair: own multi-GB file
        if _vro(variant) != "off":
            suf += "_ro"           # permuted-artifact stacks: own file (the
            # in-memory key carries ':ro' too, so a raw-order stack can
            # never serve a +ro candidate or vice versa)
        return os.path.join(
            args.cache_dir, f"layouts_hyb_{tag}_{occ}_{budget}{suf}.pkl")

    hyb_variants = {variant_key(v): v for v in candidates
                    if v[0] == "hybrid"}
    ell_path = os.path.join(args.cache_dir, f"layouts_ell_{tag}.pkl")
    ell_ovl_path = os.path.join(args.cache_dir, f"layouts_ell_ovl_{tag}.pkl")
    gat_path = os.path.join(args.cache_dir, f"layouts_gat_{tag}.pkl")
    layout_cache = _try_load(ell_path, log) or {}
    if any(variant_key(v) == "ell:ovl" for v in candidates):
        layout_cache.update(_try_load(ell_ovl_path, log) or {})
    if args.model == "gat":
        layout_cache.update(_try_load(gat_path, log) or {})
    for v in hyb_variants.values():
        layout_cache.update(_try_load(hyb_path_for(v), log) or {})
    if layout_cache:
        log(f"  layout cache: {sorted(layout_cache)}")
    lc_keys0 = set(layout_cache)

    def persist_layouts():
        nonlocal lc_keys0
        for key in set(layout_cache) - lc_keys0:
            path = (ell_path if key == "ell"
                    else ell_ovl_path if key == "ell:ovl"
                    else gat_path if key == "gat"
                    else hyb_path_for(hyb_variants[key]))
            _atomic_dump({key: layout_cache[key]}, path)
        lc_keys0 = set(layout_cache)
    if args.prep_only:
        for variant in candidates:
            # a GAT run caches under 'gat' (trainer's ELL-SpMM branch is
            # gcn/graphsage-only, so variant_key's 'ell' never appears)
            key = "gat" if args.model == "gat" else variant_key(variant)
            if variant[1] or key in layout_cache:   # pallas + fp8 twins
                continue                            # share the same layouts
            t0 = time.time()
            build_step_fns(make_cfg(variant), spec, art_for(variant), mesh,
                           layout_cache=layout_cache)
            persist_layouts()
            log(f"  prep {_vname(variant)}: {time.time() - t0:.1f}s")
        log(f"prep-only done: {sorted(layout_cache)}")
        return

    # obs telemetry (bnsgcn_tpu/obs.py): one bench_header + one
    # bench_variant event per gated measurement — the trajectory record
    # tools/obs_report.py --compare diffs across hardware windows
    obs_ev = None
    # the audit pointer every result JSON carries — ONE definition so the
    # per-variant history and both RESULT lines can never disagree
    obs_extra = ({"obs_log": os.path.abspath(args.obs_log)}
                 if args.obs_log else {})
    if args.obs_log:
        from bnsgcn_tpu.obs import EventLog
        obs_ev = EventLog(args.obs_log)
        obs_ev.emit("bench_header", workload=_workload_tag(args),
                    model=args.model, epochs=args.epochs,
                    hidden=args.hidden, layers=args.layers,
                    dtype=args.dtype, graph=args.graph,
                    candidates=[_vname(v) for v in candidates])

    for variant in candidates:
        name = _vname(variant)
        if best is not None and time.time() - t_start > args.budget_s:
            log(f"  budget {args.budget_s:.0f}s exceeded; skipping {name}")
            continue
        try:
            try:
                built = setup_and_compile(variant)
            finally:
                persist_layouts()     # keep layouts even if compile failed
            l0 = float(built[6])      # first-step (forward-dominated) loss
            quantized = variant[2] != "native" or variant[3] == "int8"
            # multi-device variants (+repN replica mean, +featT psum-order
            # drift) are gated wider and must never become native twins —
            # 'base' strips their suffixes, so without this exclusion a
            # feat2 run's loss would silently gate its quantized siblings
            multi_dev = _vrep(variant) > 1 or _vfeat(variant) > 1
            # +hrK reuses up-to-(K-1)-epoch-stale halos BY DESIGN: its
            # trajectory legitimately drifts from the exact exchange, so it
            # rides the widened gate and never becomes a native twin either
            stale = _vhr(variant) > 1
            # +ro permutes rows: the forward is the same aggregation at
            # round-off distance, but the row-position-keyed dropout draws
            # land on different nodes — a differently-seeded sample of the
            # same estimator, exactly the +repN situation — so it rides the
            # widened gate and never becomes the native twin its raw-order
            # siblings gate against
            ro = _vro(variant) != "off"
            # +at anneals K mid-run: its trajectory carries the staleness
            # drift of every rung it visits, so it rides the widened gate
            # like +hrK and never becomes a native twin
            at = _vat(variant) != "off"
            base = variant[0] + ("+pallas" if variant[1] else "")
            # quantized variants gate against their NATIVE TWIN (same SpMM
            # base, native gathers/tiles) at 5%: the twin isolates exactly
            # the quantizers' legitimate loss. Only when the twin wasn't
            # measured (a --candidates pick) fall back to the ell anchor,
            # slightly widened for the ell-vs-hybrid tiling difference.
            # +repN losses are the MEAN over N independent BNS/dropout draws
            # — a different (lower-variance, but differently-seeded) sample
            # of the same estimator — so they get the widened gate too
            # (+featT only reorders float sums, but shares the exclusion).
            if quantized and base in native_l0:
                gate0, tol0, gsrc = native_l0[base], 0.05, f"native {base}"
            elif quantized or multi_dev or stale or ro or at:
                gate0, tol0, gsrc = ref_loss, 0.07, "ell anchor"
            else:
                gate0, tol0, gsrc = ref_loss, 0.02, "ell anchor"
            if (gate0 is not None
                    and not (abs(l0 - gate0) <= tol0 * abs(gate0) + 1e-3)):
                log(f"  spmm={name} step-0 loss {l0:.4f} != {gsrc} "
                    f"{gate0:.4f} (tol {tol0:.0%}); DISCARDED")
                continue
            at_sched = None
            if at:
                from bnsgcn_tpu import tune as tune_mod
                at_sched = {ep: ch for ep, ch in
                            tune_mod.bench_schedule(args.epochs) if ep > 0}
            et, mt, loss = measure(built, name, at_sched)
        except Exception as ex:       # pragma: no cover - fallback path
            log(f"  spmm={name} failed ({type(ex).__name__}: {ex}); "
                f"falling back")
            continue
        lf = float(loss)
        if ref_loss is None:
            ref_loss, ref_final = l0, lf
        if (variant == anchor and jax.default_backend() == "tpu"
                and not args.profile_dir):
            _record_anchor(args, l0, lf)
        # end-of-run gate exercises the BACKWARD too (a miscompiled gradient
        # diverges the trajectory); same twin-first gating as step 0
        if quantized and base in native_lf:
            gate_f, tol, gsrc = native_lf[base], 0.05, f"native {base}"
        elif quantized or multi_dev or stale or ro or at:
            gate_f, tol, gsrc = ref_final, 0.07, "ell anchor"
        else:
            gate_f, tol, gsrc = ref_final, 0.02, "ell anchor"
        if not (abs(lf - gate_f) <= tol * abs(gate_f) + 1e-3):
            log(f"  spmm={name} final loss {lf:.4f} != {gsrc} "
                f"{gate_f:.4f} (tol {tol:.0%}); DISCARDED")
            continue
        if not quantized and not multi_dev and not stale and not ro \
                and not at:
            # record the twin reference only for a native run that passed
            # BOTH gates — a diverged native run must never become the
            # gate its quantized twins are judged against
            native_l0[base], native_lf[base] = l0, lf
        log(f"  spmm={name}: {et:.4f}s/epoch loss={lf:.4f}")
        pred = perf_pred.get(name) or {}
        if pred:
            # the residual line: the perf trajectory doubles as
            # calibration data from here on (gate 4 audits the drift)
            log(f"  [perf] {name}: predicted "
                f"{pred['predicted_step_s']:.4f}s/epoch "
                f"({(pred['predicted_step_s'] - et) / max(et, 1e-9):+.1%} "
                f"residual), steady wire "
                f"{pred['predicted_wire_mb']:.2f} MB/epoch")
        if obs_ev is not None:
            obs_ev.emit("bench_variant", name=name, epoch_s=round(et, 4),
                        min_epoch_s=round(mt, 4), loss=round(lf, 4),
                        backend=jax.default_backend(),
                        profiled=bool(args.profile_dir), **pred)
        try:
            # structured per-candidate history (append-only) — the winner
            # JSON line only carries the best, but cross-window analysis
            # needs every gated measurement with its context
            with open(os.path.join(args.cache_dir, "results_log.jsonl"),
                      "a") as f:
                f.write(json.dumps({
                    "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
                    "workload": _workload_tag(args), "spmm": name,
                    "epoch_s": round(et, 4), "min_epoch_s": round(mt, 4),
                    "loss": round(lf, 4),
                    "backend": jax.default_backend(),
                    "profiled": bool(args.profile_dir),
                    # the obs-log path makes this measurement post-hoc
                    # auditable: obs_report --compare two windows' logs
                    **pred, **obs_extra}) + "\n")
        except Exception:
            pass
        if best is None or et < best[0]:
            best = (et, mt, loss, name, built[-1])
            # a gated, measured epoch time: persist it so future
            # carried-forward lines report real hardware data (the round-3
            # advisor found this was promised but never written). TPU only —
            # a BNSGCN_BENCH_ALLOW_CPU smoke run must never masquerade as
            # carried-forward hardware data
            if jax.default_backend() == "tpu" and not args.profile_dir:
                _record_best(args, et, name)
            # provisional line: if an outer timeout kills the process before
            # all candidates run, the LAST printed JSON is still a valid
            # best-so-far result (the driver parses from the tail)
            print(json.dumps({
                "metric": _metric_name(args),
                **({"status": "profiled-diagnostic"} if args.profile_dir
                   else {}),
                "value": round(et, 4), "unit": "s/epoch",
                **({"vs_baseline": round(BASELINE_EPOCH_S / et, 3)}
                   if args.model == "graphsage" else {}),
                **obs_extra,
            }), flush=True)
        del built
    if best is None and args.skip_anchor and ref_loss is not None:
        # every picked candidate was gated out/failed against the stored
        # anchor — deterministic, relaunching cannot help (rc=2, same
        # contract as argument rejection); the supervisor's carried-forward
        # line already reported the stored best
        log("  no candidate survived its gates under --skip-anchor; "
            "nothing to report")
        sys.exit(2)
    assert best is not None, "no SpMM variant built"
    epoch_t, min_t, loss, spmm_used, hbm = best
    log(f"winner: spmm={spmm_used}")
    eps = g.n_edges / epoch_t
    log(f"epoch time mean={epoch_t:.4f}s min={min_t:.4f}s "
        f"({eps / 1e6:.1f}M edges/s/chip; baseline {BASELINE_EPOCH_S}s/rank) "
        f"loss={float(loss):.4f} spmm={spmm_used} "
        f"static HBM ~{hbm:.0f} MB (reference peak: 2087 MB)")

    print(json.dumps({
        "metric": _metric_name(args),
        # a traced run's first chunk pays profiler overhead: tag it so the
        # driver never records it as a clean hardware measurement
        **({"status": "profiled-diagnostic"} if args.profile_dir else {}),
        "value": round(epoch_t, 4),
        "unit": "s/epoch",
        **({"vs_baseline": round(BASELINE_EPOCH_S / epoch_t, 3)}
           if args.model == "graphsage" else {}),
        **obs_extra,
    }))
    if obs_ev is not None:
        obs_ev.emit("bench_end", winner=spmm_used,
                    epoch_s=round(epoch_t, 4), min_epoch_s=round(min_t, 4))
        obs_ev.close()


if __name__ == "__main__":
    main()
