"""Flagship benchmark — prints ONE JSON line for the driver.

Workload: the reference's headline config (BASELINE.md / reference
scripts/reddit.sh: Reddit, GraphSAGE 4-layer hidden=256, use_pp, BNS rate 0.1,
P=2) measured as per-chip epoch time. The real Reddit dataset is not
downloadable here (zero egress), so the bench runs a synthetic graph matching
one rank's share of Reddit's shape: N/2 = 116,482 nodes with Reddit's ~49
mean out-degree (~5.8M local edges) plus a 10%-sampled halo workload — i.e.
the same nodes/edges/feature widths rank 0 processes per epoch in the
baseline (README.md:94-95: 0.3578 s/epoch on 2x NVIDIA >=11GB GPUs).

vs_baseline = baseline_epoch_time / measured_epoch_time  (>1 == faster than
the reference's per-GPU epoch time).

Usage: python bench.py [--epochs N] [--scale S] [--dtype bf16|f32] [--json-only]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

BASELINE_EPOCH_S = 0.3578   # reference README.md:94 (rank 0, Reddit P=2 rate=0.1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--scale", type=float, default=0.5,
                    help="fraction of Reddit's 232,965 nodes per chip (0.5 = rank share at P=2)")
    ap.add_argument("--avg-degree", type=int, default=49)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--dtype", choices=["f32", "bf16"], default="f32")
    ap.add_argument("--edge-chunk", type=int, default=2_000_000)
    ap.add_argument("--json-only", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from bnsgcn_tpu.config import Config
    from bnsgcn_tpu.data.artifacts import build_artifacts
    from bnsgcn_tpu.data.graph import synthetic_graph
    from bnsgcn_tpu.data.partitioner import partition_graph
    from bnsgcn_tpu.models.gnn import ModelSpec, init_params
    from bnsgcn_tpu.parallel.mesh import make_parts_mesh
    from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                    init_training, place_blocks, place_replicated)

    log = (lambda *a: None) if args.json_only else (lambda *a: print(*a, file=sys.stderr))

    n_nodes = max(int(232_965 * args.scale), 2000)
    log(f"building synthetic reddit-share graph: {n_nodes} nodes x deg {args.avg_degree}")
    t0 = time.time()
    g = synthetic_graph(n_nodes=n_nodes, avg_degree=args.avg_degree,
                        n_feat=602, n_class=41, seed=0, power_law=True)
    log(f"  graph ready in {time.time() - t0:.1f}s: {g.n_edges} edges")

    pid = partition_graph(g, 1)
    art = build_artifacts(g, pid, edge_mult=args.edge_chunk)
    cfg = Config(model="graphsage", n_layers=args.layers, n_hidden=args.hidden,
                 use_pp=True, dropout=0.5, lr=0.01, sampling_rate=0.1,
                 edge_chunk=args.edge_chunk,
                 n_feat=art.n_feat, n_class=art.n_class, n_train=art.n_train)
    sizes = (art.n_feat,) + (args.hidden,) * (args.layers - 1) + (art.n_class,)
    spec = ModelSpec("graphsage", sizes, norm="layer", dropout=0.5,
                     use_pp=True, train_size=art.n_train)

    mesh = make_parts_mesh(1)
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    blk_np = build_block_arrays(art, spec.model)
    if args.dtype == "bf16":
        for k in ("feat", "in_norm", "out_norm"):
            blk_np[k] = blk_np[k].astype(np.float32)  # keep norms f32; feat cast below
        blk_np["feat"] = blk_np["feat"].astype(jnp.bfloat16)
    blk = place_blocks(blk_np, mesh)
    tables_d = place_replicated(tables, mesh)
    blk["feat"] = fns.precompute(blk, place_replicated(tables_full, mesh))
    if args.dtype == "bf16":
        blk["feat"] = blk["feat"].astype(dtype)

    params, state = init_params(jax.random.key(0), spec, dtype=dtype)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh)
    skey, dkey = jax.random.key(0), jax.random.key(1)

    log("compiling + warmup...")
    t0 = time.time()
    params, state, opt, loss = fns.train_step(params, state, opt, jnp.uint32(0),
                                              blk, tables_d, skey, dkey)
    loss.block_until_ready()
    log(f"  first step (compile) {time.time() - t0:.1f}s, loss={float(loss):.4f}")

    times = []
    for e in range(1, args.epochs + 1):
        t0 = time.perf_counter()
        params, state, opt, loss = fns.train_step(params, state, opt, jnp.uint32(e),
                                                  blk, tables_d, skey, dkey)
        loss.block_until_ready()
        times.append(time.perf_counter() - t0)
    epoch_t = float(np.mean(times))
    log(f"epoch time mean={epoch_t:.4f}s min={np.min(times):.4f}s "
        f"(baseline {BASELINE_EPOCH_S}s) loss={float(loss):.4f}")

    print(json.dumps({
        "metric": "reddit_flagship_epoch_time_per_chip",
        "value": round(epoch_t, 4),
        "unit": "s/epoch",
        "vs_baseline": round(BASELINE_EPOCH_S / epoch_t, 3),
    }))


if __name__ == "__main__":
    main()
